-- more string functions (common/function/string)

SELECT reverse('abc');
----
reverse('abc')
cba

SELECT lpad('7', 3, '0'), rpad('7', 3, '0');
----
lpad('7', 3, '0')|rpad('7', 3, '0')
007|700

SELECT split_part('a,b,c', ',', 2);
----
split_part('a,b,c', ',', 2)
b

SELECT starts_with('greptime', 'grep'), ends_with('greptime', 'time');
----
starts_with('greptime', 'grep')|ends_with('greptime', 'time')
true|true

SELECT strpos('greptime', 'ep');
----
strpos('greptime', 'ep')
3

SELECT repeat('ab', 3);
----
repeat('ab', 3)
ababab

SELECT char_length('hello');
----
char_length('hello')
5

SELECT left('greptime', 4), right('greptime', 4);
----
left('greptime', 4)|right('greptime', 4)
grep|time

