-- TRUNCATE then reinsert: identity and stats reset
CREATE TABLE ti (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ti VALUES (1000, 'a', 1.0), (2000, 'b', 2.0);

TRUNCATE TABLE ti;
----
affected_rows
0

SELECT count(*) FROM ti;
----
count(*)
0

INSERT INTO ti VALUES (1000, 'a', 9.0);

SELECT g, v FROM ti;
----
g|v
a|9.0

DROP TABLE ti;
