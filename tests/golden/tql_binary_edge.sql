-- TQL binary operator edges: vector/scalar precedence, bool modifier,
-- set operations (reference: common/tql/)
CREATE TABLE tb (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, val DOUBLE);

INSERT INTO tb VALUES (0, 'a', 2.0), (0, 'b', 8.0);

TQL EVAL (0, 0, '10s') tb * 2 + 1;
----
ts|value|host
0|5.0|a
0|17.0|b

TQL EVAL (0, 0, '10s') tb > bool 5;
----
ts|value|host
0|0.0|a
0|1.0|b

TQL EVAL (0, 0, '10s') tb > 5;
----
ts|value|host
0|8.0|b

TQL EVAL (0, 0, '10s') -tb;
----
ts|value|host
0|-2.0|a
0|-8.0|b

TQL EVAL (0, 0, '10s') tb ^ 2 % 3;
----
ts|value|host
0|1.0|a
0|1.0|b

DROP TABLE tb;
