-- RANGE ... FILL variants (common/range/fill.sql)

CREATE TABLE r (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO r (ts, host, v) VALUES (0, 'a', 1), (10000, 'a', 3), (40000, 'a', 9);

SELECT ts, host, avg(v) RANGE '10s' FROM r ALIGN '10s' BY (host) ORDER BY ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|3.0
40000|a|9.0

SELECT ts, host, avg(v) RANGE '10s' FILL NULL FROM r ALIGN '10s' BY (host) ORDER BY ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|3.0
20000|a|NULL
30000|a|NULL
40000|a|9.0

SELECT ts, host, avg(v) RANGE '10s' FILL PREV FROM r ALIGN '10s' BY (host) ORDER BY ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|3.0
20000|a|3.0
30000|a|3.0
40000|a|9.0

SELECT ts, host, avg(v) RANGE '10s' FILL 0 FROM r ALIGN '10s' BY (host) ORDER BY ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|3.0
20000|a|0.0
30000|a|0.0
40000|a|9.0

SELECT ts, host, avg(v) RANGE '10s' FILL LINEAR FROM r ALIGN '10s' BY (host) ORDER BY ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|3.0
20000|a|5.0
30000|a|7.0
40000|a|9.0

DROP TABLE r;

