-- join variants: LEFT/RIGHT/FULL/CROSS, USING, non-equi conditions
CREATE TABLE jl (ts TIMESTAMP TIME INDEX, k STRING PRIMARY KEY, v DOUBLE);

CREATE TABLE jr (ts TIMESTAMP TIME INDEX, k STRING PRIMARY KEY, w DOUBLE);

INSERT INTO jl VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0);

INSERT INTO jr VALUES (1000, 'b', 20.0), (2000, 'c', 30.0), (3000, 'd', 40.0);

SELECT l.k, l.v, r.w FROM jl l LEFT JOIN jr r ON l.k = r.k ORDER BY l.k;
----
k|v|w
a|1.0|NULL
b|2.0|20.0
c|3.0|30.0

SELECT l.k, r.k, r.w FROM jl l RIGHT JOIN jr r ON l.k = r.k ORDER BY r.k;
----
k|k|w
b|b|20.0
c|c|30.0
NULL|d|40.0

SELECT l.k, r.k FROM jl l FULL JOIN jr r ON l.k = r.k ORDER BY l.k, r.k;
----
k|k
a|NULL
b|b
c|c
NULL|d

SELECT count(*) FROM jl l CROSS JOIN jr r;
----
count(*)
9

SELECT l.k, l.v, r.w FROM jl l JOIN jr r ON l.k = r.k AND r.w > 25.0 ORDER BY l.k;
----
k|v|w
c|3.0|30.0

DROP TABLE jl;

DROP TABLE jr;
