-- fulltext matches() over an indexed column (append-mode log shape)
CREATE TABLE logs (ts TIMESTAMP TIME INDEX, msg STRING FULLTEXT) WITH (append_mode = 'true');

INSERT INTO logs VALUES (1000, 'error: disk full on node-3'), (2000, 'request completed ok'), (3000, 'disk pressure warning');

SELECT ts FROM logs WHERE matches(msg, 'disk') ORDER BY ts;
----
ts
1000
3000

SELECT ts FROM logs WHERE matches(msg, 'disk full') ORDER BY ts;
----
ts
1000

SELECT count(*) FROM logs WHERE matches(msg, 'nothing_matches');
----
count(*)
0

DROP TABLE logs;
