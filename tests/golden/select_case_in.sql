-- CASE / IN / BETWEEN expressions (common/select)

CREATE TABLE sc (v BIGINT, ts TIMESTAMP TIME INDEX);

INSERT INTO sc (v, ts) VALUES (1, 1000), (2, 2000), (3, 3000), (4, 4000);

SELECT v, CASE WHEN v < 2 THEN 'low' WHEN v < 4 THEN 'mid' ELSE 'high' END AS c FROM sc ORDER BY v;
----
v|c
1|low
2|mid
3|mid
4|high

SELECT v FROM sc WHERE v IN (2, 4) ORDER BY v;
----
v
2
4

SELECT v FROM sc WHERE v NOT IN (2, 4) ORDER BY v;
----
v
1
3

SELECT v FROM sc WHERE v BETWEEN 2 AND 3 ORDER BY v;
----
v
2
3

SELECT CASE 2 WHEN 1 THEN 'one' WHEN 2 THEN 'two' END;
----
CASE ...
two

DROP TABLE sc;

