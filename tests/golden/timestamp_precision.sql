-- Timestamp precisions + literals (common/timestamp)

CREATE TABLE tp (ts TIMESTAMP(3) TIME INDEX, v DOUBLE);

INSERT INTO tp (ts, v) VALUES ('1970-01-01 00:00:01', 1.0), ('1970-01-01 00:00:02.500', 2.0);

SELECT ts, v FROM tp ORDER BY ts;
----
ts|v
1000|1.0
2500|2.0

SELECT count(*) FROM tp WHERE ts >= '1970-01-01 00:00:02';
----
count(*)
1

SELECT max(ts) FROM tp;
----
max(ts)
2500

DROP TABLE tp;

