-- Decimal128 behavior (ports the semantics covered by the reference's
-- tests/cases/standalone/common/types/decimal/ suite onto this engine:
-- exact-scale rendering, ordering, casts, arithmetic; engine computes
-- decimals as float64 — datatypes/types.py TypeId.DECIMAL)

CREATE TABLE decimals (d DECIMAL(3, 2), ts TIMESTAMP TIME INDEX);

INSERT INTO decimals VALUES (0.1, 1000), (0.2, 2000);

SELECT d FROM decimals ORDER BY ts;
----
d
0.10
0.20

SELECT d FROM decimals ORDER BY d DESC;
----
d
0.20
0.10

SELECT d FROM decimals WHERE d = '0.1'::DECIMAL(3,2);
----
d
0.10

-- different scale on the comparison side still matches numerically
SELECT d FROM decimals WHERE d >= '0.1'::DECIMAL(9,5) ORDER BY d;
----
d
0.10
0.20

INSERT INTO decimals VALUES (0.11, 3000), (0.21, 4000);

SELECT d FROM decimals WHERE d > '0.1'::DECIMAL(9,1) ORDER BY d;
----
d
0.11
0.20
0.21

-- scalar functions over decimal casts
SELECT ABS('-0.1'::DECIMAL(4,3)) AS a, CEIL('10.5'::DECIMAL(4,1)) AS c;
----
a|c
0.1|11.0

SELECT FLOOR('-10.5'::DECIMAL(4,1)) AS f, ROUND('2.5'::DECIMAL(4,1)) AS r;
----
f|r
-11.0|2.0

-- arithmetic promotes to double
SELECT d + 1 FROM decimals WHERE ts <= 2000 ORDER BY ts;
----
d + 1
1.1
1.2

-- aggregates over decimal
SELECT count(d) AS n, sum(d) AS s, max(d) AS m FROM decimals;
----
n|s|m
4|0.62|0.21

-- describe reports the exact type
SHOW COLUMNS FROM decimals LIKE 'd';
----
Column|Type|Null|Key|Default
d|decimal(3,2)|Yes||

-- out-of-range decimal declarations error
CREATE TABLE bad (d DECIMAL(99, 2), ts TIMESTAMP TIME INDEX);
----
ERROR

DROP TABLE decimals;
