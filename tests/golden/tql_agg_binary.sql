-- TQL aggregation + scalar binary ops (common/tql)

CREATE TABLE v (ts TIMESTAMP TIME INDEX, dc STRING PRIMARY KEY, greptime_value DOUBLE);

INSERT INTO v (ts, dc, greptime_value) VALUES
  (0, 'east', 4), (0, 'west', 6), (10000, 'east', 8), (10000, 'west', 12);

TQL EVAL (0, 10, '10s') sum(v);
----
ts|value
0|10.0
10000|20.0

TQL EVAL (0, 10, '10s') avg(v);
----
ts|value
0|5.0
10000|10.0

TQL EVAL (0, 10, '10s') max(v) - min(v);
----
ts|value
0|2.0
10000|4.0

TQL EVAL (0, 10, '10s') v * 2;
----
ts|value|dc
0|8.0|east
0|12.0|west
10000|16.0|east
10000|24.0|west

TQL EVAL (0, 10, '10s') sum by (dc) (v + 1);
----
ts|value|dc
0|5.0|east
0|7.0|west
10000|9.0|east
10000|13.0|west

DROP TABLE v;

