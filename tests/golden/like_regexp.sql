-- LIKE / regexp matching (common/select)

CREATE TABLE lk (s STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO lk (s, ts) VALUES ('apple', 1000), ('banana', 2000), ('cherry', 3000), ('Avocado', 4000);

SELECT s FROM lk WHERE s LIKE 'a%' ORDER BY s;
----
s
apple

SELECT s FROM lk WHERE s LIKE '%an%' ORDER BY s;
----
s
banana

SELECT s FROM lk WHERE s LIKE '_herry' ORDER BY s;
----
s
cherry

SELECT s FROM lk WHERE s NOT LIKE 'a%' ORDER BY s;
----
s
Avocado
banana
cherry

SELECT s FROM lk WHERE regexp_match(s, '^[ab]') ORDER BY s;
----
s
apple
banana

DROP TABLE lk;

