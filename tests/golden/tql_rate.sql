-- TQL rate/increase/delta over counters (common/tql + promql/)

CREATE TABLE m (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE);

INSERT INTO m (ts, host, greptime_value) VALUES
  (0, 'a', 0), (30000, 'a', 30), (60000, 'a', 60), (90000, 'a', 90);

TQL EVAL (60, 90, '30s') rate(m[1m]);
----
ts|value|host
60000|1.0|a
90000|1.0|a

TQL EVAL (60, 90, '30s') increase(m[1m]);
----
ts|value|host
60000|60.0|a
90000|60.0|a

TQL EVAL (60, 90, '30s') delta(m[1m]);
----
ts|value|host
60000|60.0|a
90000|60.0|a

DROP TABLE m;

