-- COPY TO / COPY FROM round-trips (reference:
-- tests/cases/standalone/common/copy/)
CREATE TABLE src_csv (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO src_csv VALUES (1000, 'a', 1.5), (2000, 'b', 2.5), (3000, 'c', NULL);

COPY src_csv TO '/tmp/golden_copy_rt.csv' WITH (format = 'csv');
----
affected_rows
3

CREATE TABLE dst_csv (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

COPY dst_csv FROM '/tmp/golden_copy_rt.csv' WITH (format = 'csv');
----
affected_rows
3

SELECT host, v FROM dst_csv ORDER BY host;
----
host|v
a|1.5
b|2.5
c|NULL

COPY src_csv TO '/tmp/golden_copy_rt.parquet' WITH (format = 'parquet');
----
affected_rows
3

CREATE TABLE dst_pq (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

COPY dst_pq FROM '/tmp/golden_copy_rt.parquet' WITH (format = 'parquet');
----
affected_rows
3

SELECT host, v FROM dst_pq ORDER BY host;
----
host|v
a|1.5
b|2.5
c|NULL

DROP TABLE src_csv;

DROP TABLE dst_csv;

DROP TABLE dst_pq;
