-- NULL ordering and DISTINCT semantics (reference: common/order +
-- common/aggregate/distinct sqlness areas)

CREATE TABLE s (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO s VALUES
  (1000, 'a', 2.0), (2000, 'b', NULL), (3000, 'c', 1.0),
  (4000, 'd', 2.0), (5000, 'e', NULL);

-- SQL default: NULLS LAST for ASC
SELECT host, v FROM s ORDER BY v, host;
----
host|v
c|1.0
a|2.0
d|2.0
b|NULL
e|NULL

-- and NULLS FIRST for DESC
SELECT host, v FROM s ORDER BY v DESC, host LIMIT 3;
----
host|v
b|NULL
e|NULL
a|2.0

SELECT host FROM s ORDER BY v NULLS FIRST, host LIMIT 2;
----
host
b
e

-- DISTINCT treats NULLs as one group
SELECT DISTINCT v FROM s ORDER BY v;
----
v
1.0
2.0
NULL

SELECT count(DISTINCT v) FROM s;
----
count(DISTINCT v)
2

-- aggregates skip NULLs
SELECT count(v) AS c, sum(v) AS s, avg(v) AS a FROM s;
----
c|s|a
3|5.0|1.66667

DROP TABLE s;
