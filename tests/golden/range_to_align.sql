-- RANGE ALIGN TO anchors and BY subsets
CREATE TABLE ra (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, dc STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ra VALUES (0, 'h1', 'e', 1.0), (3600000, 'h1', 'e', 2.0), (0, 'h2', 'w', 10.0), (3600000, 'h2', 'w', 20.0);

SELECT ts, dc, sum(v) RANGE '1h' FROM ra ALIGN '1h' BY (dc) ORDER BY ts, dc;
----
ts|dc|sum(v) RANGE 3600000ms
0|e|1.0
0|w|10.0
3600000|e|2.0
3600000|w|20.0

SELECT ts, sum(v) RANGE '2h' FROM ra ALIGN '1h' BY () ORDER BY ts;
----
ts|sum(v) RANGE 7200000ms
-3600000|11.0
0|33.0
3600000|22.0

SELECT ts, host, dc, avg(v) RANGE '1h' FROM ra ALIGN '1h' TO '1970-01-01 00:30:00' ORDER BY ts, host;
----
ts|host|dc|avg(v) RANGE 3600000ms
-1800000|h1|e|1.0
-1800000|h2|w|10.0
1800000|h1|e|2.0
1800000|h2|w|20.0

DROP TABLE ra;
