-- JOIN semantics (capability port of the reference sqlness join cases,
-- /root/reference/tests/cases/standalone/common/select/ + dml joins)
CREATE TABLE t1 (k STRING, x DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(k));

CREATE TABLE t2 (k STRING, y DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(k));

INSERT INTO t1 (k, x, ts) VALUES ('a', 1.0, 1000), ('b', 2.0, 1000), ('c', 3.0, 2000);

INSERT INTO t2 (k, y, ts) VALUES ('a', 10.0, 1000), ('b', 20.0, 1000), ('d', 40.0, 2000);

SELECT t1.k, x, y FROM t1 JOIN t2 ON t1.k = t2.k ORDER BY t1.k;
----
k|x|y
a|1.0|10.0
b|2.0|20.0

SELECT t1.k, x, y FROM t1 LEFT JOIN t2 ON t1.k = t2.k ORDER BY t1.k;
----
k|x|y
a|1.0|10.0
b|2.0|20.0
c|3.0|NULL

SELECT t2.k, x, y FROM t1 RIGHT JOIN t2 ON t1.k = t2.k ORDER BY t2.k;
----
k|x|y
a|1.0|10.0
b|2.0|20.0
d|NULL|40.0

SELECT t1.k, t2.k, x, y FROM t1 FULL JOIN t2 ON t1.k = t2.k ORDER BY x NULLS LAST;
----
k|k|x|y
a|a|1.0|10.0
b|b|2.0|20.0
c|NULL|3.0|NULL
NULL|d|NULL|40.0

SELECT k, x, y FROM t1 JOIN t2 USING (k) ORDER BY k;
----
k|x|y
a|1.0|10.0
b|2.0|20.0

-- non-equi residual on top of the equi pair
SELECT t1.k, x, y FROM t1 JOIN t2 ON t1.k = t2.k AND y > 15 ORDER BY t1.k;
----
k|x|y
b|2.0|20.0

-- cross join
SELECT count(*) FROM t1 CROSS JOIN t2;
----
count(*)
9

-- comma cross join with where acting as join condition
SELECT a.k, b.y FROM t1 a, t2 b WHERE a.k = b.k ORDER BY a.k;
----
k|y
a|10.0
b|20.0

-- aggregate over a join
SELECT a.k, sum(a.x + b.y) AS s FROM t1 a JOIN t2 b ON a.k = b.k GROUP BY a.k ORDER BY s;
----
k|s
a|11.0
b|22.0

-- join on time index + tag
SELECT t1.k, x, y FROM t1 JOIN t2 ON t1.k = t2.k AND t1.ts = t2.ts ORDER BY t1.k;
----
k|x|y
a|1.0|10.0
b|2.0|20.0

-- outer join without any equality is rejected
SELECT * FROM t1 LEFT JOIN t2 ON t1.x < t2.y;
----
ERROR

-- WHERE on the null-supplying side filters AFTER the outer join
SELECT t1.k, y FROM t1 LEFT JOIN t2 ON t1.k = t2.k WHERE y = 10;
----
k|y
a|10.0

-- USING key coalesces across sides on right-only rows
SELECT k, y FROM t1 RIGHT JOIN t2 USING (k) ORDER BY y;
----
k|y
a|10.0
b|20.0
d|40.0
