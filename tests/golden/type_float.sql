-- Float semantics: precision, infinities via division, NaN ordering (common/types/float)

CREATE TABLE f (v DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO f (v, ts) VALUES (0.1, 1000), (0.2, 2000), (1e300, 3000), (-1e300, 4000);

SELECT sum(v) FROM f WHERE ts < 3000;
----
sum(v)
0.3

SELECT v FROM f ORDER BY v LIMIT 1;
----
v
-1e+300

SELECT v * 2 FROM f WHERE ts = 3000;
----
v * 2
2e+300

SELECT round(0.1 + 0.2, 10);
----
round(0.1 + 0.2, 10)
0.3

SELECT 1.0 / 3.0;
----
1.0 / 3.0
0.333333

DROP TABLE f;

