-- date/time scalar functions (common/function/time.sql)

SELECT date_bin('1 hour', CAST(5400000 AS TIMESTAMP));
----
date_bin(INTERVAL '1 hour', CAST(5400000 AS timestamp_ms))
3600000

SELECT date_trunc('day', CAST('1970-01-02 13:14:15' AS TIMESTAMP));
----
date_trunc('day', CAST('1970-01-02 13:14:15' AS timestamp_ms))
86400000

SELECT extract(hour FROM CAST('1970-01-01 05:30:00' AS TIMESTAMP));
----
extract('hour', CAST('1970-01-01 05:30:00' AS timestamp_ms))
5.0

SELECT extract(minute FROM CAST('1970-01-01 05:30:00' AS TIMESTAMP));
----
extract('minute', CAST('1970-01-01 05:30:00' AS timestamp_ms))
30.0

SELECT to_unixtime('1970-01-02 00:00:00');
----
to_unixtime('1970-01-02 00:00:00')
86400

