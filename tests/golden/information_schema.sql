-- information_schema surface (reference sqlness:
-- common/system/information_schema.sql)
CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY (host));

SELECT table_name, table_type, engine FROM information_schema.tables WHERE table_schema = 'public';
----
table_name|table_type|engine
m|BASE TABLE|mito

SELECT column_name, semantic_type, is_nullable FROM information_schema.columns WHERE table_name = 'm' ORDER BY column_name;
----
column_name|semantic_type|is_nullable
host|TAG|No
ts|TIMESTAMP|No
v|FIELD|Yes

SELECT constraint_name, column_name, ordinal_position FROM information_schema.key_column_usage WHERE table_name = 'm' ORDER BY constraint_name;
----
constraint_name|column_name|ordinal_position
PRIMARY|host|1
TIME INDEX|ts|1

SELECT constraint_type FROM information_schema.table_constraints WHERE table_name = 'm' ORDER BY constraint_type;
----
constraint_type
PRIMARY KEY
TIME INDEX

SELECT engine, support FROM information_schema.engines ORDER BY engine;
----
engine|support
file|YES
metric|YES
tsdb|DEFAULT

-- cluster_info now reflects the REAL topology (fleet plane): one
-- STANDALONE row here, datanode/frontend/metasrv rows in dist runs —
-- assert the shape-stable invariant instead of a fixed peer list
SELECT count(*) >= 1, min(status) != '' FROM information_schema.cluster_info;
----
count(*) >= 1|min(status) != ''
true|true

CREATE VIEW vw AS SELECT host FROM m;

SELECT table_name FROM information_schema.views;
----
table_name
vw

SELECT schema_name FROM information_schema.schemata ORDER BY schema_name;
----
schema_name
public
