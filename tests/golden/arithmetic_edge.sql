-- arithmetic edge cases: division by zero, modulo, integer/float mixing
-- (reference: common/select/, common/function/)
CREATE TABLE ar (ts TIMESTAMP TIME INDEX, a BIGINT, b DOUBLE);

INSERT INTO ar VALUES (1000, 7, 2.0), (2000, -7, 0.0), (3000, 0, 3.5);

SELECT a / 2 FROM ar ORDER BY ts;
----
a / 2
3
-4
0

SELECT b / 0.0 FROM ar ORDER BY ts;
----
b / 0.0
NULL
NULL
NULL

SELECT a % 3 FROM ar ORDER BY ts;
----
a % 3
1
2
0

SELECT a + b, a - b, a * b FROM ar ORDER BY ts;
----
a + b|a - b|a * b
9.0|5.0|14.0
-7.0|-7.0|-0.0
3.5|-3.5|0.0

SELECT abs(a), sign(b) FROM ar ORDER BY ts;
----
abs(a)|sign(b)
7.0|1.0
7.0|0.0
0.0|1.0

SELECT round(b / 3.0, 2) FROM ar ORDER BY ts;
----
round(b / 3.0, 2)
0.67
0.0
1.17

SELECT power(a, 2), sqrt(abs(a)) FROM ar ORDER BY ts;
----
power(a, 2)|sqrt(abs(a))
49.0|2.64575
49.0|2.64575
0.0|0.0

DROP TABLE ar;
