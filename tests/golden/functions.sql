-- Scalar functions (reference sqlness: common/function/)
CREATE TABLE f (s STRING, x DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(s));

INSERT INTO f (s, x, ts) VALUES ('Hello', 2.0, 1000), ('world', -3.5, 2000);

SELECT s, upper(s) AS u, lower(s) AS l, length(s) AS n FROM f ORDER BY s;
----
s|u|l|n
Hello|HELLO|hello|5
world|WORLD|world|5

SELECT abs(x) AS a, round(x) AS r, ceil(x) AS c, floor(x) AS fl FROM f ORDER BY x;
----
a|r|c|fl
3.5|-4.0|-3.0|-4.0
2.0|2.0|2.0|2.0

SELECT sqrt(4.0) AS sq, pow(2.0, 10.0) AS p, ln(1.0) AS l;
----
sq|p|l
2.0|1024.0|0.0

SELECT concat(s, '!') AS c FROM f ORDER BY s;
----
c
Hello!
world!

SELECT CASE WHEN x > 0 THEN 'pos' ELSE 'neg' END AS sign FROM f ORDER BY x;
----
sign
neg
pos

SELECT coalesce(NULL, 'fallback') AS c;
----
c
fallback

SELECT x, x::BIGINT AS i FROM f ORDER BY x;
----
x|i
-3.5|-3
2.0|2

SELECT greatest(1.0, 2.0) AS g, least(1.0, 2.0) AS l;
----
g|l
2.0|1.0
