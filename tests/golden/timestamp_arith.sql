-- timestamp arithmetic + interval literals (common/timestamp)

CREATE TABLE ta (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO ta (ts, v) VALUES (3600000, 1.0), (7200000, 2.0);

SELECT ts + INTERVAL '1 hour' FROM ta ORDER BY ts;
----
ts + INTERVAL '1 hour'
7200000
10800000

SELECT ts - INTERVAL '30 minutes' FROM ta ORDER BY ts;
----
ts - INTERVAL '30 minutes'
1800000
5400000

SELECT count(*) FROM ta WHERE ts > '1970-01-01 00:30:00';
----
count(*)
2

SELECT v FROM ta WHERE ts = CAST('1970-01-01 01:00:00' AS TIMESTAMP);
----
v
1.0

DROP TABLE ta;

