-- string function edges: empty strings, unicode, padding, split
-- (reference: common/function/)
CREATE TABLE se (ts TIMESTAMP TIME INDEX, s STRING);

INSERT INTO se VALUES (1000, 'Hello World'), (2000, ''), (3000, 'héllo');

SELECT length(s), upper(s) FROM se ORDER BY ts;
----
length(s)|upper(s)
11|HELLO WORLD
0|
5|HÉLLO

SELECT substr(s, 1, 5), replace(s, 'l', 'L') FROM se ORDER BY ts;
----
substr(s, 1, 5)|replace(s, 'l', 'L')
Hello|HeLLo WorLd
|
héllo|héLLo

SELECT trim('  pad  '), lpad('7', 3, '0'), rpad('7', 3, '.');
----
trim('  pad  ')|lpad('7', 3, '0')|rpad('7', 3, '.')
pad|007|7..

SELECT concat(s, '!'), reverse(s) FROM se ORDER BY ts;
----
concat(s, '!')|reverse(s)
Hello World!|dlroW olleH
!|
héllo!|olléh

SELECT split_part('a,b,c', ',', 2);
----
split_part('a,b,c', ',', 2)
b

SELECT starts_with(s, 'He'), ends_with(s, 'ld') FROM se ORDER BY ts;
----
starts_with(s, 'He')|ends_with(s, 'ld')
true|true
false|false
false|false

SELECT strpos(s, 'World') FROM se ORDER BY ts;
----
strpos(s, 'World')
7
0
0

DROP TABLE se;
