-- ORDER BY edge cases: expressions, mixed directions, NULLS placement,
-- aliases, and ordinal errors (reference: tests/cases/standalone/common/order/)
CREATE TABLE ob (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE, w BIGINT);

INSERT INTO ob VALUES (1000, 'a', 3.0, 30), (2000, 'b', 1.0, NULL), (3000, 'c', NULL, 10), (4000, 'd', 2.0, 20);

SELECT g, v FROM ob ORDER BY v;
----
g|v
b|1.0
d|2.0
a|3.0
c|NULL

SELECT g, v FROM ob ORDER BY v DESC;
----
g|v
c|NULL
a|3.0
d|2.0
b|1.0

SELECT g, v FROM ob ORDER BY v NULLS FIRST;
----
g|v
c|NULL
b|1.0
d|2.0
a|3.0

SELECT g, v FROM ob ORDER BY v DESC NULLS LAST;
----
g|v
a|3.0
d|2.0
b|1.0
c|NULL

SELECT g, w FROM ob ORDER BY w NULLS FIRST, g DESC;
----
g|w
b|NULL
c|10
d|20
a|30

SELECT g, v * -1 AS neg FROM ob ORDER BY neg;
----
g|neg
a|-3.0
d|-2.0
b|-1.0
c|NULL

SELECT g FROM ob ORDER BY v + w;
----
g
d
a
b
c

SELECT g, v FROM ob ORDER BY upper(g) DESC;
----
g|v
d|2.0
c|NULL
b|1.0
a|3.0

SELECT g FROM ob ORDER BY missing_col;
----
ERROR

SELECT g, v FROM ob ORDER BY v LIMIT 2 OFFSET 1;
----
g|v
d|2.0
a|3.0

DROP TABLE ob;
