-- DISTINCT edges: multi-column, with NULLs, count distinct multiple args
CREATE TABLE dd (ts TIMESTAMP TIME INDEX, a STRING, b DOUBLE);

INSERT INTO dd VALUES (1000, 'x', 1.0), (2000, 'x', 1.0), (3000, 'x', NULL), (4000, 'y', NULL), (5000, 'y', 2.0);

SELECT DISTINCT a, b FROM dd ORDER BY a, b;
----
a|b
x|1.0
x|NULL
y|2.0
y|NULL

SELECT count(DISTINCT a) FROM dd;
----
count(DISTINCT a)
2

SELECT count(DISTINCT b) FROM dd;
----
count(DISTINCT b)
2

SELECT a, count(DISTINCT b) FROM dd GROUP BY a ORDER BY a;
----
a|count(DISTINCT b)
x|1
y|1

DROP TABLE dd;
