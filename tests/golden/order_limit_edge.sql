-- ORDER BY / LIMIT / OFFSET edge cases (common/order)

CREATE TABLE ol (v BIGINT, s STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO ol (v, s, ts) VALUES (3, 'c', 1000), (1, 'a', 2000), (2, 'b', 3000);

SELECT v FROM ol ORDER BY v DESC;
----
v
3
2
1

SELECT v FROM ol ORDER BY v LIMIT 2;
----
v
1
2

SELECT v FROM ol ORDER BY v LIMIT 1 OFFSET 1;
----
v
2

SELECT v FROM ol ORDER BY v LIMIT 0;
----
v

SELECT v, s FROM ol ORDER BY s DESC, v ASC;
----
v|s
3|c
2|b
1|a

SELECT v FROM ol ORDER BY v + 0 DESC;
----
v
3
2
1

SELECT v AS k FROM ol ORDER BY k;
----
k
1
2
3

DROP TABLE ol;

