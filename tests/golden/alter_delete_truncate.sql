-- ALTER TABLE / DELETE / TRUNCATE behavior (ports the semantics of the
-- reference's tests/cases/standalone/common/{alter,delete,truncate}/)

CREATE TABLE monitor (
  ts TIMESTAMP TIME INDEX,
  host STRING PRIMARY KEY,
  cpu DOUBLE
);

INSERT INTO monitor VALUES
  (1000, 'a', 1.0), (2000, 'a', 2.0), (1000, 'b', 10.0), (3000, 'b', 30.0);

-- add a column: existing rows read NULL for it
ALTER TABLE monitor ADD COLUMN memory DOUBLE;

SELECT host, cpu, memory FROM monitor ORDER BY ts, host;
----
host|cpu|memory
a|1.0|NULL
b|10.0|NULL
a|2.0|NULL
b|30.0|NULL

INSERT INTO monitor (ts, host, cpu, memory) VALUES (4000, 'a', 4.0, 64.0);

SELECT host, cpu, memory FROM monitor WHERE memory IS NOT NULL;
----
host|cpu|memory
a|4.0|64.0

-- delete one series row by primary key + time
DELETE FROM monitor WHERE host = 'b' AND ts = 1000;

SELECT host, cpu FROM monitor ORDER BY ts, host;
----
host|cpu
a|1.0
a|2.0
b|30.0
a|4.0

-- drop the added column
ALTER TABLE monitor DROP COLUMN memory;

SELECT * FROM monitor WHERE host = 'a' ORDER BY ts LIMIT 1;
----
ts|host|cpu
1000|a|1.0

-- rename
ALTER TABLE monitor RENAME monitor2;

SELECT count(cpu) FROM monitor2;
----
count(cpu)
4

SELECT count(cpu) FROM monitor;
----
ERROR

TRUNCATE TABLE monitor2;

SELECT count(cpu) FROM monitor2;
----
count(cpu)
0

DROP TABLE monitor2;
