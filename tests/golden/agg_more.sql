-- additional aggregate coverage (common/aggregate + function)

CREATE TABLE am (g STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(g));

INSERT INTO am (g, v, ts) VALUES ('a', 1, 1000), ('a', 2, 2000), ('a', 3, 3000), ('b', 10, 1000), ('b', 30, 2000);

SELECT g, first_value(v ORDER BY ts) AS f, last_value(v ORDER BY ts) AS l FROM am GROUP BY g ORDER BY g;
----
g|f|l
a|1.0|3.0
b|10.0|30.0

SELECT g, var_pop(v) FROM am GROUP BY g ORDER BY g;
----
g|var_pop(v)
a|0.666667
b|100.0

SELECT median(v) FROM am;
----
median(v)
3.0

SELECT g, count(*) FROM am GROUP BY g ORDER BY count(*) DESC;
----
g|count(*)
a|3
b|2

SELECT sum(v) + count(*) FROM am;
----
sum(v) + count(*)
51.0

SELECT avg(v * v) - avg(v) * avg(v) AS variance FROM am WHERE g = 'a';
----
variance
0.666667

DROP TABLE am;

