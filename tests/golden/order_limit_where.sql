-- ORDER BY / LIMIT / WHERE pruning (reference sqlness: common/order/,
-- common/select/limit cases)
CREATE TABLE t (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO t (host, v, ts) VALUES
  ('a', 5, 1000), ('b', 3, 2000), ('c', 8, 3000), ('d', 1, 4000), ('e', 6, 5000);

SELECT host, v FROM t ORDER BY v DESC LIMIT 2;
----
host|v
c|8.0
e|6.0

SELECT host, v FROM t ORDER BY v LIMIT 2 OFFSET 1;
----
host|v
b|3.0
a|5.0

SELECT host FROM t WHERE ts >= 3000 AND ts < 5000 ORDER BY host;
----
host
c
d

SELECT host FROM t WHERE ts BETWEEN 2000 AND 3000 ORDER BY host;
----
host
b
c

SELECT host FROM t WHERE host IN ('a', 'd', 'nope') ORDER BY host;
----
host
a
d

SELECT host FROM t WHERE host LIKE 'b%' OR v > 7 ORDER BY host;
----
host
b
c

SELECT host, v FROM t WHERE v BETWEEN 3 AND 6 AND host != 'e' ORDER BY v DESC;
----
host|v
a|5.0
b|3.0

SELECT host, v * 10 AS scaled FROM t WHERE NOT (v < 5) ORDER BY scaled DESC LIMIT 2;
----
host|scaled
c|80.0
e|60.0
