-- NULL comparison / IS NULL / coalesce (common/select + function)

CREATE TABLE nl (v DOUBLE, s STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO nl (v, s, ts) VALUES (1.0, 'x', 1000);

INSERT INTO nl (ts) VALUES (2000);

SELECT v IS NULL, s IS NOT NULL FROM nl ORDER BY ts;
----
v IS NULL|s IS NOT NULL
false|true
true|false

SELECT count(*) FROM nl WHERE v IS NULL;
----
count(*)
1

SELECT coalesce(v, -1.0) FROM nl ORDER BY ts;
----
coalesce(v, -1.0)
1.0
-1.0

SELECT coalesce(s, 'missing') FROM nl ORDER BY ts;
----
coalesce(s, 'missing')
x
missing

SELECT v = NULL FROM nl ORDER BY ts;
----
v = NULL
NULL
NULL

SELECT nullif(1, 1), nullif(2, 1);
----
nullif(1, 1)|nullif(2, 1)
NULL|2

DROP TABLE nl;

