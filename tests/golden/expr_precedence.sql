-- operator precedence + arithmetic edge cases (common/select/arithmetic)

SELECT 2 + 3 * 4;
----
2 + 3 * 4
14

SELECT (2 + 3) * 4;
----
2 + 3 * 4
20

SELECT 10 / 4;
----
10 / 4
2

SELECT 10.0 / 4;
----
10.0 / 4
2.5

SELECT 10 % 3;
----
10 % 3
1

SELECT -2 * 3;
----
-2 * 3
-6

SELECT 2 * 3 > 5 AND 1 < 2;
----
2 * 3 > 5 AND 1 < 2
true

SELECT NOT true OR true;
----
NOT True OR True
true

SELECT 1 + 2 = 3;
----
1 + 2 = 3
true

