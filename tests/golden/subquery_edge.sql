-- subquery edges: scalar subquery in WHERE/items, IN subquery, derived tables
CREATE TABLE sq (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO sq VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0);

SELECT g FROM sq WHERE v > (SELECT avg(v) FROM sq) ORDER BY g;
----
g
c

SELECT g, (SELECT max(v) FROM sq) AS mx FROM sq ORDER BY g;
----
g|mx
a|3.0
b|3.0
c|3.0

SELECT g FROM sq WHERE g IN (SELECT g FROM sq WHERE v >= 2.0) ORDER BY g;
----
g
b
c

SELECT t.g, t.w FROM (SELECT g, v * 2 AS w FROM sq) t WHERE t.w > 2.0 ORDER BY t.g;
----
g|w
b|4.0
c|6.0

DROP TABLE sq;
