-- LIKE/ILIKE patterns and regexp matching
CREATE TABLE lk (ts TIMESTAMP TIME INDEX, s STRING);

INSERT INTO lk VALUES (1000, 'alpha'), (2000, 'ALPHA'), (3000, 'beta_x'), (4000, '100%');

SELECT s FROM lk WHERE s LIKE 'al%' ORDER BY ts;
----
s
alpha

SELECT s FROM lk WHERE s ILIKE 'AL%' ORDER BY ts;
----
ERROR <<InvalidSyntaxError: unsupported statement 'ILIKE' at 25>>

SELECT s FROM lk WHERE s LIKE '%\_x' ORDER BY ts;
----
s
beta_x

SELECT s FROM lk WHERE s NOT LIKE '%a%' ORDER BY ts;
----
s
ALPHA
100%

SELECT s FROM lk WHERE s ~ '^[ab]' ORDER BY ts;
----
ERROR <<InvalidSyntaxError: unexpected character '~' at 25>>

DROP TABLE lk;
