-- GROUP BY / HAVING edges: expressions as keys, HAVING on aliases,
-- HAVING without GROUP BY (reference: common/aggregate/)
CREATE TABLE gh (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO gh VALUES (1000, 'ax', 1.0), (2000, 'ay', 2.0), (3000, 'bx', 3.0), (4000, 'by', 4.0);

SELECT substr(g, 1, 1) AS fam, sum(v) FROM gh GROUP BY fam ORDER BY fam;
----
fam|sum(v)
a|3.0
b|7.0

SELECT substr(g, 1, 1) AS fam, count(*) AS n FROM gh GROUP BY fam HAVING n > 1 ORDER BY fam;
----
fam|n
a|2
b|2

SELECT substr(g, 1, 1) AS fam, sum(v) AS s FROM gh GROUP BY fam HAVING s > 6.0;
----
fam|s
b|7.0

SELECT sum(v) AS total FROM gh HAVING sum(v) > 5.0;
----
total
10.0

SELECT sum(v) AS total FROM gh HAVING sum(v) > 100.0;
----
total

SELECT g, avg(v) FROM gh GROUP BY g HAVING avg(v) >= 3.0 ORDER BY g;
----
g|avg(v)
bx|3.0
by|4.0

DROP TABLE gh;
