-- signed month/year intervals must carry their sign through calendar
-- arithmetic (date_add with INTERVAL '-1 month' SUBTRACTS), with
-- end-of-month clamping intact (ADVICE r5)
SELECT date_add(to_timestamp_millis(0), INTERVAL '-1 month');
----
date_add(to_timestamp_millis(0), INTERVAL '-1 month')
-2678400000

SELECT date_sub(to_timestamp_millis(0), INTERVAL '-1 month');
----
date_sub(to_timestamp_millis(0), INTERVAL '-1 month')
2678400000

-- 2024-03-31 minus one month clamps to 2024-02-29 (leap year)
SELECT date_add(TIMESTAMP '2024-03-31 00:00:00', INTERVAL '-1 month');
----
date_add(CAST('2024-03-31 00:00:00' AS timestamp_ms), INTERVAL '-1 month')
1709164800000

-- 2024-02-29 minus one year clamps to 2023-02-28
SELECT date_add(TIMESTAMP '2024-02-29 00:00:00', INTERVAL '-1 year');
----
date_add(CAST('2024-02-29 00:00:00' AS timestamp_ms), INTERVAL '-1 year')
1677542400000

-- mixed signs total 11 months (1970-12-01)
SELECT date_add(to_timestamp_millis(0), INTERVAL '1 year -1 month');
----
date_add(to_timestamp_millis(0), INTERVAL '1 year -1 month')
28857600000

-- fixed-span units keep their sign too
SELECT date_add(to_timestamp_millis(0), INTERVAL '-1 day');
----
date_add(to_timestamp_millis(0), INTERVAL '-1 day')
-86400000

SELECT to_timestamp_millis(3600000) + INTERVAL '-1 hour';
----
to_timestamp_millis(3600000) + INTERVAL '-1 hour'
0
