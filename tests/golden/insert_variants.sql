-- INSERT variants (common/insert)

CREATE TABLE iv (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE, note STRING DEFAULT 'none');

INSERT INTO iv VALUES (1000, 'a', 1.0, 'x');

INSERT INTO iv (host, ts) VALUES ('b', 2000);

INSERT INTO iv (ts, host, v) VALUES (3000, 'c', 3.0), (4000, 'd', 4.0);

SELECT ts, host, v, note FROM iv ORDER BY ts;
----
ts|host|v|note
1000|a|1.0|x
2000|b|NULL|none
3000|c|3.0|none
4000|d|4.0|none

CREATE TABLE iv2 (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO iv2 SELECT ts, host, v FROM iv WHERE v > 2;

SELECT host, v FROM iv2 ORDER BY host;
----
host|v
c|3.0
d|4.0

INSERT INTO iv (ts, host, bogus) VALUES (5000, 'e', 1);
----
ERROR

DROP TABLE iv;

DROP TABLE iv2;

