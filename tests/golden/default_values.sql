-- column DEFAULTs: literals and omitted-column inserts
CREATE TABLE dv (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE DEFAULT 6.5, n BIGINT DEFAULT 42, s STRING DEFAULT 'none');

INSERT INTO dv (ts, g) VALUES (1000, 'a');

INSERT INTO dv (ts, g, v) VALUES (2000, 'b', 1.0);

SELECT g, v, n, s FROM dv ORDER BY g;
----
g|v|n|s
a|6.5|42|none
b|1.0|42|none

DROP TABLE dv;
