-- CAST edges: string<->number, timestamp casts, boolean, failures
CREATE TABLE cr (ts TIMESTAMP TIME INDEX, s STRING, v DOUBLE);

INSERT INTO cr VALUES (1000, '42', 1.9), (2000, '-3.5', 2.1);

SELECT CAST(s AS DOUBLE) FROM cr ORDER BY ts;
----
CAST(s AS float64)
42.0
-3.5

SELECT CAST(v AS BIGINT) FROM cr ORDER BY ts;
----
CAST(v AS int64)
1
2

SELECT CAST(v AS STRING) FROM cr ORDER BY ts;
----
CAST(v AS string)
1.9
2.1

SELECT CAST(1 AS BOOLEAN), CAST(0 AS BOOLEAN);
----
CAST(1 AS bool)|CAST(0 AS bool)
true|false

SELECT s::DOUBLE + 1 FROM cr ORDER BY ts;
----
CAST(s AS float64) + 1
43.0
-2.5

-- unparsable strings cast to NULL (TRY_CAST-style lenient semantics)
SELECT CAST('nope' AS DOUBLE);
----
CAST('nope' AS float64)
NULL

DROP TABLE cr;
