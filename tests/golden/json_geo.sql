-- JSON / geo / network scalar families (reference sqlness:
-- common/function/json/, common/function/geo.sql)
CREATE TABLE j (doc STRING, ip STRING, lat DOUBLE, lon DOUBLE, ts TIMESTAMP TIME INDEX);

INSERT INTO j (doc, ip, lat, lon, ts) VALUES
  ('{"a": {"b": 3}, "name": "x", "ok": true}', '10.1.2.3', 37.7749, -122.4194, 1000),
  ('not json', '192.168.0.9', 40.7128, -74.0060, 2000);

SELECT json_get_int(doc, '$.a.b') AS b, json_get_string(doc, 'name') AS n FROM j ORDER BY ts;
----
b|n
3|x
NULL|NULL

SELECT json_is_object(doc) AS o, json_path_exists(doc, '$.ok') AS e FROM j ORDER BY ts;
----
o|e
true|true
false|false

SELECT ts FROM j WHERE json_get_bool(doc, 'ok');
----
ts
1000

SELECT geohash(lat, lon, 4) AS g FROM j ORDER BY ts;
----
g
9q8y
dr5r

SELECT round(st_distance(lat, lon, 37.7749, -122.4194) / 1000.0) AS km FROM j ORDER BY ts;
----
km
0.0
4129.0

SELECT ipv4_num_to_string(ipv4_string_to_num(ip)) AS rt FROM j ORDER BY ts;
----
rt
10.1.2.3
192.168.0.9

SELECT ts FROM j WHERE ipv4_in_range(ip, '10.0.0.0/8');
----
ts
1000
