-- percentile aggregates (quantile family)
CREATE TABLE ap (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ap VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0), (4000, 'd', 4.0), (5000, 'e', 100.0);

SELECT approx_percentile_cont(0.5, v) FROM ap;
----
approx_percentile_cont(0.5, v)
3.0

SELECT median(v) FROM ap;
----
median(v)
3.0

SELECT percentile_cont(0.25) WITHIN GROUP (ORDER BY v) FROM ap;
----
percentile_cont(0.25, v)
2.0

SELECT percentile_cont(0.25) WITHIN GROUP (ORDER BY v DESC) FROM ap;
----
percentile_cont(1.0 - 0.25, v)
4.0

DROP TABLE ap;
