-- table options surface: append_mode duplicates, SHOW CREATE carries options
CREATE TABLE am (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE) WITH (append_mode = 'true');

INSERT INTO am VALUES (1000, 'a', 1.0);

INSERT INTO am VALUES (1000, 'a', 2.0);

SELECT g, v FROM am ORDER BY v;
----
g|v
a|1.0
a|2.0

SELECT count(*) FROM am;
----
count(*)
2

DROP TABLE am;
