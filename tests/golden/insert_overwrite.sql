-- last-write-wins upserts: same (tags, ts) key overwrites fields
CREATE TABLE iw (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, a DOUBLE, b DOUBLE);

INSERT INTO iw VALUES (1000, 'x', 1.0, 10.0);

INSERT INTO iw VALUES (1000, 'x', 2.0, 20.0);

SELECT g, a, b FROM iw;
----
g|a|b
x|2.0|20.0

-- partial-column overwrite nulls the omitted field (last_row mode)
INSERT INTO iw (ts, g, a) VALUES (1000, 'x', 3.0);

SELECT g, a, b FROM iw;
----
g|a|b
x|3.0|NULL

SELECT count(*) FROM iw;
----
count(*)
1

DROP TABLE iw;
