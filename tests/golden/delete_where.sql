-- DELETE with predicates (common/delete)

CREATE TABLE del (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO del (ts, host, v) VALUES (1000, 'a', 1), (2000, 'a', 2), (1000, 'b', 3), (2000, 'b', 4);

DELETE FROM del WHERE host = 'a' AND ts = 1000;

SELECT host, ts, v FROM del ORDER BY host, ts;
----
host|ts|v
a|2000|2.0
b|1000|3.0
b|2000|4.0

DELETE FROM del WHERE host = 'b';

SELECT host, ts, v FROM del ORDER BY host, ts;
----
host|ts|v
a|2000|2.0

SELECT count(*) FROM del;
----
count(*)
1

DROP TABLE del;

