-- cluster/runtime information_schema tables exist and have sane shapes
SELECT count(*) >= 1 FROM information_schema.build_info;
----
count(*) >= 1
true

SELECT count(*) >= 1 FROM information_schema.engines;
----
count(*) >= 1
true

SELECT count(*) >= 1 FROM information_schema.character_sets;
----
count(*) >= 1
true

SELECT table_name FROM information_schema.tables WHERE table_schema = 'information_schema' ORDER BY table_name LIMIT 5;
----
table_name
