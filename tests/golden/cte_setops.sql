-- CTEs and set operations (reference sqlness: common/cte/, common/select/
-- union cases)
CREATE TABLE nums (v DOUBLE, tag STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(tag));

INSERT INTO nums (v, tag, ts) VALUES (1, 'a', 1000), (2, 'b', 2000), (3, 'c', 3000), (4, 'd', 4000);

WITH small AS (SELECT v, tag FROM nums WHERE v <= 2) SELECT * FROM small ORDER BY v;
----
v|tag
1.0|a
2.0|b

WITH small AS (SELECT v FROM nums WHERE v <= 2), big AS (SELECT v FROM nums WHERE v > 2)
SELECT small.v AS sv, big.v AS bv FROM small JOIN big ON small.v + 2 = big.v ORDER BY sv;
----
sv|bv
1.0|3.0
2.0|4.0

-- CTE shadows a base table name
WITH nums AS (SELECT v FROM nums WHERE v = 1) SELECT * FROM nums;
----
v
1.0

SELECT v FROM nums WHERE v < 2 UNION ALL SELECT v FROM nums WHERE v > 3;
----
v
1.0
4.0

SELECT tag FROM nums WHERE v < 3 UNION SELECT tag FROM nums WHERE v < 2 ORDER BY tag;
----
tag
a
b

SELECT v FROM nums WHERE v < 3 INTERSECT SELECT v FROM nums WHERE v > 1;
----
v
2.0

SELECT v FROM nums EXCEPT SELECT v FROM nums WHERE v > 1 ORDER BY v;
----
v
1.0

SELECT v FROM nums UNION ALL SELECT v FROM nums WHERE v = 1 ORDER BY v LIMIT 3;
----
v
1.0
1.0
2.0

-- column count mismatch
SELECT v, tag FROM nums UNION SELECT v FROM nums;
----
ERROR

-- a parenthesized operand keeps its own ORDER BY / LIMIT; the trailing
-- clauses after the parens bind to the compound
SELECT v FROM nums WHERE v = 1 UNION ALL (SELECT v FROM nums ORDER BY v DESC LIMIT 1) ORDER BY v;
----
v
1.0
4.0

-- INTERSECT binds tighter than UNION (standard SQL)
SELECT 1 AS v UNION SELECT 2 INTERSECT SELECT 2 ORDER BY v;
----
v
1
2

-- EXCEPT ALL removes one left copy per right row (bag semantics)
SELECT * FROM (SELECT v FROM nums WHERE v < 2 UNION ALL SELECT 1.0) u EXCEPT ALL SELECT 1.0;
----
v
1.0

-- NULLs compare equal in set operations
SELECT NULL AS x, 1 AS y INTERSECT SELECT NULL, 1;
----
x|y
NULL|1
