-- SHOW CREATE TABLE reflects ALTERs (reference: common/show/)
CREATE TABLE sca (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

ALTER TABLE sca ADD COLUMN w BIGINT;

SHOW CREATE TABLE sca;
----
Table|Create Table
sca|CREATE TABLE IF NOT EXISTS `sca` (
  `ts` TIMESTAMP(3) NOT NULL,
  `host` STRING NOT NULL,
  `v` DOUBLE,
  `w` BIGINT,
  TIME INDEX (`ts`),
  PRIMARY KEY (`host`)
)
ENGINE=mito

ALTER TABLE sca DROP COLUMN w;

SHOW CREATE TABLE sca;
----
Table|Create Table
sca|CREATE TABLE IF NOT EXISTS `sca` (
  `ts` TIMESTAMP(3) NOT NULL,
  `host` STRING NOT NULL,
  `v` DOUBLE,
  TIME INDEX (`ts`),
  PRIMARY KEY (`host`)
)
ENGINE=mito

ALTER TABLE sca RENAME sca2;

SHOW CREATE TABLE sca2;
----
Table|Create Table
sca2|CREATE TABLE IF NOT EXISTS `sca2` (
  `ts` TIMESTAMP(3) NOT NULL,
  `host` STRING NOT NULL,
  `v` DOUBLE,
  TIME INDEX (`ts`),
  PRIMARY KEY (`host`)
)
ENGINE=mito

DROP TABLE sca2;
