-- Subqueries: scalar, IN, EXISTS, FROM (reference sqlness:
-- common/select/ subquery coverage)
CREATE TABLE s (k STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(k));

INSERT INTO s (k, v, ts) VALUES ('a', 1, 1000), ('b', 5, 2000), ('c', 9, 3000);

SELECT k, v FROM s WHERE v > (SELECT avg(v) FROM s) ORDER BY k;
----
k|v
c|9.0

SELECT (SELECT max(v) FROM s) + 1 AS m;
----
m
10.0

SELECT k FROM s WHERE k IN (SELECT k FROM s WHERE v >= 5) ORDER BY k;
----
k
b
c

SELECT k FROM s WHERE k NOT IN (SELECT k FROM s WHERE v >= 5) ORDER BY k;
----
k
a

SELECT count(*) AS c FROM s WHERE EXISTS (SELECT 1 FROM s WHERE v > 100);
----
c
0

SELECT count(*) AS c FROM s WHERE NOT EXISTS (SELECT 1 FROM s WHERE v > 100);
----
c
3

SELECT sub.k, sub.doubled FROM (SELECT k, v * 2 AS doubled FROM s WHERE v > 1) sub ORDER BY sub.k;
----
k|doubled
b|10.0
c|18.0

SELECT max(doubled) AS m FROM (SELECT v * 2 AS doubled FROM s) d;
----
m
18.0

-- scalar subquery with more than one row errors
SELECT (SELECT v FROM s);
----
ERROR
