-- timestamp function edges: date_trunc/date_bin/extract/formatting
-- (reference: common/timestamp/, common/function/)
CREATE TABLE tf (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO tf VALUES (1705329015123, 1.0), (1705332615000, 2.0);

SELECT date_trunc('hour', ts) FROM tf ORDER BY ts;
----
date_trunc('hour', ts)
1705327200000
1705330800000

SELECT date_trunc('day', ts) FROM tf ORDER BY ts;
----
date_trunc('day', ts)
1705276800000
1705276800000

SELECT date_bin('30 minutes', ts) FROM tf ORDER BY ts;
----
date_bin(INTERVAL '30 minutes', ts)
1705329000000
1705332600000

SELECT extract(hour FROM ts), extract(minute FROM ts) FROM tf ORDER BY ts;
----
extract('hour', ts)|extract('minute', ts)
14.0|30.0
15.0|30.0

SELECT to_unixtime(ts) FROM tf ORDER BY ts;
----
to_unixtime(ts)
1705329015
1705332615

SELECT date_format(ts, '%Y-%m-%d %H:%M:%S') FROM tf ORDER BY ts;
----
date_format(ts, '%Y-%m-%d %H:%M:%S')
2024-01-15 14:30:15
2024-01-15 15:30:15

DROP TABLE tf;
