-- SUM/AVG with NULLs and empty inputs (common/aggregate/sum.sql)

CREATE TABLE s (v DOUBLE, n BIGINT, ts TIMESTAMP TIME INDEX);

INSERT INTO s (v, n, ts) VALUES (1.5, 10, 1000), (2.5, 20, 2000);

INSERT INTO s (ts) VALUES (3000);

SELECT sum(v), avg(v) FROM s;
----
sum(v)|avg(v)
4.0|2.0

SELECT sum(n), avg(n) FROM s;
----
sum(n)|avg(n)
30|15.0

SELECT sum(v) FROM s WHERE v > 100;
----
sum(v)
NULL

SELECT sum(v + n) FROM s;
----
sum(v + n)
34.0

SELECT sum(v * 2), avg(v * 2) FROM s;
----
sum(v * 2)|avg(v * 2)
8.0|4.0

DROP TABLE s;

