-- Session variables + ADMIN functions + MySQL-compat SHOW family
-- (reference: tests/cases/standalone/common/show/ +
-- src/sql/src/statements/admin.rs behaviors)

CREATE TABLE host_metrics (
  ts TIMESTAMP TIME INDEX,
  host STRING PRIMARY KEY,
  cpu DOUBLE
);

INSERT INTO host_metrics VALUES (1000, 'a', 1.5), (2000, 'b', 2.5);

SET time_zone = '+08:00';

SHOW VARIABLES LIKE 'time_zone';
----
Variable_name|Value
time_zone|+08:00

SET autocommit = 1, sql_mode = ANSI;

SHOW VARIABLES LIKE 'autocommit';
----
Variable_name|Value
autocommit|1

SHOW COLUMNS FROM host_metrics;
----
Column|Type|Null|Key|Default
ts|timestamp_ms|No|TIME INDEX|
host|string|No|PRI|
cpu|float64|Yes||

SHOW INDEX FROM host_metrics;
----
Table|Key_name|Seq_in_index|Column_name
host_metrics|PRIMARY|1|host
host_metrics|TIME INDEX|1|ts

-- flush makes the memtable durable as an SST; second flush is a no-op
ADMIN flush_table('host_metrics');
----
ADMIN flush_table('host_metrics')
1

ADMIN flush_table('host_metrics');
----
ADMIN flush_table('host_metrics')
0

-- data survives the flush
SELECT host, cpu FROM host_metrics ORDER BY ts;
----
host|cpu
a|1.5
b|2.5

ADMIN compact_table('host_metrics');
----
ADMIN compact_table('host_metrics')
0

ADMIN kill('424242');
----
ADMIN kill('424242')
0

ADMIN no_such_function();
----
ERROR

DROP TABLE host_metrics;
