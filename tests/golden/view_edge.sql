-- view edges: view over view, view with expressions, drop behavior
CREATE TABLE ve (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ve VALUES (1000, 'a', 1.0), (2000, 'b', 2.0);

CREATE VIEW v_base AS SELECT g, v * 10 AS v10 FROM ve;

CREATE VIEW v_top AS SELECT g, v10 + 1 AS v11 FROM v_base;

SELECT g, v11 FROM v_top ORDER BY g;
----
g|v11
a|11.0
b|21.0

CREATE OR REPLACE VIEW v_base AS SELECT g, v * 100 AS v10 FROM ve;

SELECT g, v11 FROM v_top ORDER BY g;
----
g|v11
a|101.0
b|201.0

DROP VIEW v_top;

SELECT g FROM v_top;
----
ERROR

DROP VIEW v_base;

DROP TABLE ve;
