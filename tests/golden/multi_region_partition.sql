-- PARTITION ON routing visible through information_schema.partitions
CREATE TABLE mr (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE) PARTITION ON COLUMNS (host) (host < 'm', host >= 'm');

INSERT INTO mr VALUES (1000, 'alpha', 1.0), (2000, 'zulu', 2.0);

SELECT count(*) FROM mr;
----
count(*)
2

SELECT host FROM mr WHERE host = 'zulu';
----
host
zulu

SELECT partition_name, partition_expression FROM information_schema.partitions WHERE table_name = 'mr' ORDER BY partition_name;
----
partition_name|partition_expression
p0|host < 'm'
p1|host >= 'm'

DROP TABLE mr;
