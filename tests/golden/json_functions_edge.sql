-- JSON function edges: extraction paths, types, invalid docs
CREATE TABLE jf (ts TIMESTAMP TIME INDEX, doc STRING);

INSERT INTO jf VALUES (1000, '{"a": 1, "b": {"c": "x"}, "arr": [10, 20]}');

SELECT json_get_int(doc, 'a') FROM jf;
----
json_get_int(doc, 'a')
1

SELECT json_get_string(doc, 'b.c') FROM jf;
----
json_get_string(doc, 'b.c')
x

SELECT json_get_int(doc, 'arr[1]') FROM jf;
----
json_get_int(doc, 'arr[1]')
20

SELECT json_get_string(doc, 'missing') FROM jf;
----
json_get_string(doc, 'missing')
NULL

SELECT json_is_object(doc), json_path_exists(doc, 'b.c'), json_path_exists(doc, 'zzz') FROM jf;
----
json_is_object(doc)|json_path_exists(doc, 'b.c')|json_path_exists(doc, 'zzz')
true|true|false

DROP TABLE jf;
