-- ALTER + FLOW interactions: flows keep aggregating across source schema
-- changes (reference: tests/cases/standalone/common/alter/ + flow/)
CREATE TABLE req (host STRING PRIMARY KEY, lat DOUBLE, ts TIMESTAMP TIME INDEX);

CREATE FLOW stats SINK TO req_sum AS SELECT date_bin('1 minute', ts) AS w, host, count(*) AS n, sum(lat) AS s FROM req GROUP BY w, host;

INSERT INTO req VALUES ('a', 10.0, 1000), ('a', 20.0, 2000);

ADMIN flush_flow('stats');
----
ADMIN flush_flow('stats')
1

SELECT host, n, s FROM req_sum ORDER BY host;
----
host|n|s
a|2.0|30.0

-- adding an unrelated column must not break the flow
ALTER TABLE req ADD COLUMN region STRING;

INSERT INTO req (host, lat, ts, region) VALUES ('b', 5.0, 3000, 'eu');

ADMIN flush_flow('stats');
----
ADMIN flush_flow('stats')
1

SELECT host, n, s FROM req_sum ORDER BY host;
----
host|n|s
a|2.0|30.0
b|1.0|5.0

SHOW FLOWS;
----
Flows
stats

ADMIN flush_flow('nope');
----
ERROR

DROP FLOW stats;

DROP TABLE req;
