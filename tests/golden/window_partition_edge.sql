-- window functions: lag/lead defaults, ntile, first/last in partition
CREATE TABLE wp (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO wp VALUES (1000, 'a', 1.0), (2000, 'a', 2.0), (3000, 'a', 3.0), (1000, 'b', 10.0), (2000, 'b', 20.0);

SELECT g, ts, lag(v) OVER (PARTITION BY g ORDER BY ts) AS prev FROM wp ORDER BY g, ts;
----
g|ts|prev
a|1000|NULL
a|2000|1.0
a|3000|2.0
b|1000|NULL
b|2000|10.0

SELECT g, ts, lead(v, 1, -1.0) OVER (PARTITION BY g ORDER BY ts) AS nxt FROM wp ORDER BY g, ts;
----
g|ts|nxt
a|1000|2.0
a|2000|3.0
a|3000|-1.0
b|1000|20.0
b|2000|-1.0

SELECT g, ts, ntile(2) OVER (PARTITION BY g ORDER BY ts) AS bucket FROM wp ORDER BY g, ts;
----
g|ts|bucket
a|1000|1
a|2000|1
a|3000|2
b|1000|1
b|2000|2

SELECT g, ts, row_number() OVER (ORDER BY v DESC) AS rn FROM wp ORDER BY rn;
----
g|ts|rn
b|2000|1
b|1000|2
a|3000|3
a|2000|4
a|1000|5

SELECT g, ts, first_value(v) OVER (PARTITION BY g ORDER BY ts) AS fv FROM wp ORDER BY g, ts;
----
g|ts|fv
a|1000|1.0
a|2000|1.0
a|3000|1.0
b|1000|10.0
b|2000|10.0

DROP TABLE wp;
