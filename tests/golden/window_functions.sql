-- SQL window functions (reference executes OVER() through DataFusion's
-- WindowAggExec; behavior ports of the sqlness window coverage)

CREATE TABLE w (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO w VALUES
  (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'a', 3.0),
  (4000, 'b', 5.0), (5000, 'a', 2.0);

SELECT ts, row_number() OVER (ORDER BY ts DESC) AS rn FROM w ORDER BY ts;
----
ts|rn
1000|5
2000|4
3000|3
4000|2
5000|1

SELECT ts, host, lag(v) OVER (PARTITION BY host ORDER BY ts) AS prev
FROM w ORDER BY ts;
----
ts|host|prev
1000|a|NULL
2000|b|NULL
3000|a|1.0
4000|b|2.0
5000|a|3.0

SELECT ts, lead(v, 1, -1) OVER (ORDER BY ts) AS nxt FROM w ORDER BY ts;
----
ts|nxt
1000|2.0
2000|3.0
3000|5.0
4000|2.0
5000|-1.0

-- running sum per partition (SQL default frame with ORDER BY)
SELECT ts, host, sum(v) OVER (PARTITION BY host ORDER BY ts) AS run
FROM w ORDER BY ts;
----
ts|host|run
1000|a|1.0
2000|b|2.0
3000|a|4.0
4000|b|7.0
5000|a|6.0

-- whole-partition aggregate (no ORDER BY in the spec)
SELECT ts, host, sum(v) OVER (PARTITION BY host) AS tot FROM w ORDER BY ts;
----
ts|host|tot
1000|a|6.0
2000|b|7.0
3000|a|6.0
4000|b|7.0
5000|a|6.0

-- ties: rank skips, dense_rank doesn't; peers share running values
SELECT ts, rank() OVER (ORDER BY v) AS r, dense_rank() OVER (ORDER BY v) AS d
FROM w ORDER BY ts;
----
ts|r|d
1000|1|1
2000|2|2
3000|4|3
4000|5|4
5000|2|2

SELECT ts, first_value(v) OVER (PARTITION BY host ORDER BY ts) AS f,
  last_value(v) OVER (PARTITION BY host ORDER BY ts
    ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS l
FROM w ORDER BY ts;
----
ts|f|l
1000|1.0|2.0
2000|2.0|5.0
3000|1.0|2.0
4000|2.0|5.0
5000|1.0|2.0

SELECT ts, avg(v) OVER (ORDER BY ts) AS running_avg FROM w ORDER BY ts;
----
ts|running_avg
1000|1.0
2000|1.5
3000|2.0
4000|2.75
5000|2.6

SELECT ts, ntile(2) OVER (ORDER BY ts) AS bucket FROM w ORDER BY ts;
----
ts|bucket
1000|1
2000|1
3000|1
4000|2
5000|2

-- percentile_cont via WITHIN GROUP
SELECT percentile_cont(0.5) WITHIN GROUP (ORDER BY v) AS med FROM w;
----
med
2.0

SELECT host, percentile_cont(0.5) WITHIN GROUP (ORDER BY v) AS med
FROM w GROUP BY host ORDER BY host;
----
host|med
a|2.0
b|3.5

-- window + GROUP BY composition is rejected, not silently wrong
SELECT host, row_number() OVER (ORDER BY sum(v)) FROM w GROUP BY host;
----
ERROR

-- unsupported explicit frames error cleanly
SELECT sum(v) OVER (ORDER BY ts ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING)
FROM w;
----
ERROR

-- OFFSET / LIMIT forms
SELECT ts FROM w ORDER BY ts LIMIT 2 OFFSET 1;
----
ts
2000
3000

SELECT ts FROM w ORDER BY ts OFFSET 3 LIMIT 5;
----
ts
4000
5000

SELECT ts FROM w ORDER BY ts LIMIT 1, 2;
----
ts
2000
3000

DROP TABLE w;
