-- Fulltext matches() (reference sqlness: common/function/matches.sql)
CREATE TABLE logs (host STRING, msg STRING FULLTEXT, ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) WITH (append_mode = 'true');

INSERT INTO logs (host, msg, ts) VALUES
  ('a', 'disk error timeout on raid', 1000),
  ('b', 'warning slow query path', 2000),
  ('c', 'all systems nominal', 3000),
  ('a', 'network error detected', 4000);

SELECT host, ts FROM logs WHERE matches(msg, 'error') ORDER BY ts;
----
host|ts
a|1000
a|4000

SELECT ts FROM logs WHERE matches(msg, 'error AND timeout');
----
ts
1000

SELECT ts FROM logs WHERE matches(msg, 'timeout OR slow') ORDER BY ts;
----
ts
1000
2000

SELECT ts FROM logs WHERE matches(msg, 'error NOT network');
----
ts
1000

SELECT ts FROM logs WHERE matches(msg, '"slow query"');
----
ts
2000

SELECT ts FROM logs WHERE matches(msg, '(disk OR network) error') ORDER BY ts;
----
ts
1000
4000

SELECT count(*) AS c FROM logs WHERE matches_term(msg, 'raid');
----
c
1
