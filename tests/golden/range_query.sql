-- RANGE queries (reference: src/query/src/range_select/plan.rs semantics,
-- sqlness common/range/)
CREATE TABLE cpu (host STRING, val DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO cpu (host, val, ts) VALUES
  ('h1', 1, 0), ('h1', 2, 5000), ('h1', 3, 10000), ('h1', 4, 15000),
  ('h2', 10, 0), ('h2', 20, 5000), ('h2', 30, 10000), ('h2', 40, 15000);

SELECT ts, host, avg(val) RANGE '10s' FROM cpu ALIGN '10s' TO '1970-01-01 00:00:00' BY (host) ORDER BY ts, host;
----
ts|host|avg(val) RANGE 10000ms
0|h1|1.5
0|h2|15.0
10000|h1|3.5
10000|h2|35.0

SELECT ts, host, max(val) RANGE '10s', min(val) RANGE '10s' FROM cpu ALIGN '10s' TO '1970-01-01 00:00:00' BY (host) ORDER BY ts, host;
----
ts|host|max(val) RANGE 10000ms|min(val) RANGE 10000ms
0|h1|2.0|1.0
0|h2|20.0|10.0
10000|h1|4.0|3.0
10000|h2|40.0|30.0

-- BY () folds all series into one group
SELECT ts, sum(val) RANGE '10s' FROM cpu ALIGN '10s' TO '1970-01-01 00:00:00' BY () ORDER BY ts;
----
ts|sum(val) RANGE 10000ms
0|33.0
10000|77.0

-- range wider than step: sliding windows labeled by window START,
-- [t, t + range) per the reference's plan.rs:1068 semantics
SELECT ts, count(val) RANGE '20s' FROM cpu ALIGN '10s' TO '1970-01-01 00:00:00' BY () ORDER BY ts;
----
ts|count(val) RANGE 20000ms
-10000|4.0
0|8.0
10000|4.0
