-- aggregates over empty inputs and all-NULL groups
CREATE TABLE eg (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

SELECT count(*), count(v), sum(v), min(v), avg(v) FROM eg;
----
count(*)|count(v)|sum(v)|min(v)|avg(v)
0|0|NULL|NULL|NULL

INSERT INTO eg (ts, g) VALUES (1000, 'a'), (2000, 'a');

SELECT g, count(*), count(v), sum(v), max(v) FROM eg GROUP BY g;
----
g|count(*)|count(v)|sum(v)|max(v)
a|2|0|NULL|NULL

SELECT count(*) FROM eg WHERE v > 100;
----
count(*)
0

DROP TABLE eg;
