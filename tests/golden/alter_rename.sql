-- ALTER TABLE RENAME (common/alter/rename.sql)

CREATE TABLE old_name (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO old_name (ts, v) VALUES (1000, 42.0);

ALTER TABLE old_name RENAME new_name;

SELECT v FROM new_name;
----
v
42.0

SELECT v FROM old_name;
----
ERROR

SHOW TABLES LIKE 'new%';
----
Tables
new_name

DROP TABLE new_name;

