-- Views (reference: src/operator/src/statement/ddl.rs create_view +
-- common/view sqlness cases)
CREATE TABLE base (host STRING, cpu DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO base (host, cpu, ts) VALUES ('h1', 10, 1000), ('h2', 90, 2000), ('h3', 50, 3000);

CREATE VIEW hot AS SELECT host, cpu FROM base WHERE cpu > 40;

SELECT * FROM hot ORDER BY host;
----
host|cpu
h2|90.0
h3|50.0

SELECT count(*) AS n FROM hot;
----
n
2

-- view joined with its base table
SELECT hot.host, base.ts FROM hot JOIN base ON hot.host = base.host ORDER BY hot.host;
----
host|ts
h2|2000
h3|3000

CREATE OR REPLACE VIEW hot AS SELECT host FROM base WHERE cpu >= 90;

SELECT * FROM hot;
----
host
h2

SHOW VIEWS;
----
Views
hot

-- a view name cannot collide with a table
CREATE VIEW base AS SELECT host FROM base;
----
ERROR

DROP VIEW hot;

SELECT * FROM hot;
----
ERROR

DROP VIEW IF EXISTS hot;
