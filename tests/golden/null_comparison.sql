-- three-valued logic in predicates: IN/NOT IN with NULLs, BETWEEN,
-- IS DISTINCT FROM-style idioms (reference: common/select/, common/types/)
CREATE TABLE nc (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO nc VALUES (1000, 'a', 1.0), (2000, 'b', NULL), (3000, 'c', 3.0);

SELECT g FROM nc WHERE v IN (1.0, 3.0) ORDER BY g;
----
g
a
c

SELECT g FROM nc WHERE v NOT IN (1.0) ORDER BY g;
----
g
c

SELECT g FROM nc WHERE v BETWEEN 0.5 AND 2.0 ORDER BY g;
----
g
a

SELECT g FROM nc WHERE NOT (v BETWEEN 0.5 AND 2.0) ORDER BY g;
----
g
c

SELECT g FROM nc WHERE v IS NULL;
----
g
b

SELECT g FROM nc WHERE v IS NOT NULL ORDER BY g;
----
g
a
c

SELECT g, v = NULL AS eq_null FROM nc ORDER BY g;
----
g|eq_null
a|NULL
b|NULL
c|NULL

SELECT g, coalesce(v, -1.0) AS cv FROM nc ORDER BY g;
----
g|cv
a|1.0
b|-1.0
c|3.0

SELECT g, nullif(v, 1.0) AS nv FROM nc ORDER BY g;
----
g|nv
a|NULL
b|NULL
c|3.0

DROP TABLE nc;
