-- count(*) vs count(col) vs count(1) over NULLs and filters
CREATE TABLE cn (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO cn (ts, g, v) VALUES (1000, 'a', 1.0), (2000, 'b', NULL), (3000, 'c', 3.0);

SELECT count(*), count(v), count(1), count(g) FROM cn;
----
count(*)|count(v)|count(1)|count(g)
3|2|3|3

SELECT count(*) FROM cn WHERE v IS NULL;
----
count(*)
1

SELECT g, count(v) FROM cn GROUP BY g ORDER BY g;
----
g|count(v)
a|1
b|0
c|1

DROP TABLE cn;
