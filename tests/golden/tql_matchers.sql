-- TQL label matchers (=, !=, =~, !~) (common/tql)

CREATE TABLE lm (ts TIMESTAMP TIME INDEX, env STRING, dc STRING, greptime_value DOUBLE, PRIMARY KEY (env, dc));

INSERT INTO lm (ts, env, dc, greptime_value) VALUES
  (0, 'prod', 'east', 1), (0, 'prod', 'west', 2), (0, 'dev', 'east', 3);

TQL EVAL (0, 0, '10s') lm{env="prod"};
----
ts|value|__name__|dc|env
0|1.0|lm|east|prod
0|2.0|lm|west|prod

TQL EVAL (0, 0, '10s') lm{env!="prod"};
----
ts|value|__name__|dc|env
0|3.0|lm|east|dev

TQL EVAL (0, 0, '10s') lm{dc=~"ea.*"};
----
ts|value|__name__|dc|env
0|1.0|lm|east|prod
0|3.0|lm|east|dev

TQL EVAL (0, 0, '10s') lm{env="prod", dc!~"we.*"};
----
ts|value|__name__|dc|env
0|1.0|lm|east|prod

DROP TABLE lm;

