-- String type + functions (common/types/string)

CREATE TABLE str (s STRING, ts TIMESTAMP TIME INDEX);

INSERT INTO str (s, ts) VALUES ('Hello', 1000), ('', 2000), ('with ''quote', 3000);

SELECT s, length(s) FROM str ORDER BY ts;
----
s|length(s)
Hello|5
|0
with 'quote|11

SELECT upper(s), lower(s) FROM str WHERE ts = 1000;
----
upper(s)|lower(s)
HELLO|hello

SELECT concat(s, '!') FROM str WHERE ts = 1000;
----
concat(s, '!')
Hello!

SELECT substr('greptime', 1, 5);
----
substr('greptime', 1, 5)
grept

SELECT trim('  pad  ');
----
trim('  pad  ')
pad

SELECT replace('aaa', 'a', 'b');
----
replace('aaa', 'a', 'b')
bbb

SELECT s FROM str WHERE s LIKE 'He%';
----
s
Hello

DROP TABLE str;

