-- math scalar functions (common/function/math)

SELECT abs(-3.5), abs(2);
----
abs(-3.5)|abs(2)
3.5|2.0

SELECT floor(2.7), ceil(2.1);
----
floor(2.7)|ceil(2.1)
2.0|3.0

SELECT round(2.567, 2);
----
round(2.567, 2)
2.57

SELECT sqrt(16.0);
----
sqrt(16.0)
4.0

SELECT power(2, 10);
----
power(2, 10)
1024.0

SELECT mod(10, 3);
----
mod(10, 3)
1

SELECT exp(0.0), ln(1.0);
----
exp(0.0)|ln(1.0)
1.0|0.0

SELECT log10(1000.0);
----
log10(1000.0)
3.0

SELECT sin(0.0), cos(0.0);
----
sin(0.0)|cos(0.0)
0.0|1.0

SELECT greatest(1, 2), least(1, 2);
----
greatest(1, 2)|least(1, 2)
2.0|1.0

