-- information_schema join/filter breadth (reference: common/information_schema/)
CREATE TABLE isj (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

SELECT t.table_name, c.column_name FROM information_schema.tables t JOIN information_schema.columns c ON c.table_name = t.table_name WHERE t.table_schema = 'public' ORDER BY c.column_name;
----
table_name|column_name
isj|host
isj|ts
isj|v

SELECT column_name, semantic_type FROM information_schema.columns WHERE table_name = 'isj' ORDER BY column_name;
----
column_name|semantic_type
host|TAG
ts|TIMESTAMP
v|FIELD

SELECT table_name FROM information_schema.tables WHERE table_schema = 'public';
----
table_name
isj

DROP TABLE isj;
