-- DELETE edges: predicate forms, delete-all, reinsert after delete
CREATE TABLE de (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO de VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0);

DELETE FROM de WHERE g = 'b';
----
affected_rows
1

SELECT g FROM de ORDER BY g;
----
g
a
c

DELETE FROM de WHERE v > 10.0;
----
affected_rows
0

SELECT count(*) FROM de;
----
count(*)
2

INSERT INTO de VALUES (2000, 'b', 20.0);

SELECT g, v FROM de ORDER BY g;
----
g|v
a|1.0
b|20.0
c|3.0

DROP TABLE de;
