-- GROUP BY expressions / aliases / positions (common/aggregate)

CREATE TABLE ge (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ge (ts, host, v) VALUES
  (1000, 'web-1', 1), (2000, 'web-2', 2), (3000, 'db-1', 10), (4000, 'db-2', 20);

SELECT substr(host, 1, 2) AS grp, sum(v) FROM ge GROUP BY grp ORDER BY grp;
----
grp|sum(v)
db|30.0
we|3.0

SELECT date_bin('2 seconds', ts) AS w, count(*) FROM ge GROUP BY w ORDER BY w;
----
w|count(*)
0|1
2000|2
4000|1

SELECT host, sum(v) FROM ge GROUP BY host HAVING sum(v) >= 10 ORDER BY host;
----
host|sum(v)
db-1|10.0
db-2|20.0

SELECT upper(substr(host, 1, 2)) AS g2, max(v) FROM ge GROUP BY g2 ORDER BY g2;
----
g2|max(v)
DB|20.0
WE|2.0

DROP TABLE ge;

