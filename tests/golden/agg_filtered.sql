-- aggregates over filtered/expression inputs
CREATE TABLE af (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO af VALUES (1000, 'a', 1.0), (2000, 'a', -2.0), (3000, 'b', 3.0), (4000, 'b', -4.0);

SELECT g, sum(abs(v)) FROM af GROUP BY g ORDER BY g;
----
g|sum(abs(v))
a|3.0
b|7.0

SELECT g, count(*) FILTER (WHERE v > 0) FROM af GROUP BY g ORDER BY g;
----
g|count(*)
a|1
b|1

SELECT g, sum(v) FILTER (WHERE v > 0) AS pos_sum FROM af GROUP BY g ORDER BY g;
----
g|pos_sum
a|1.0
b|3.0

SELECT g, max(v * v) FROM af GROUP BY g ORDER BY g;
----
g|max(v * v)
a|4.0
b|16.0

SELECT min(v), max(v), avg(v), count(v) FROM af WHERE g = 'a';
----
min(v)|max(v)|avg(v)|count(v)
-2.0|1.0|-0.5|2

DROP TABLE af;
