-- TQL EXPLAIN / ANALYZE output shape (reference:
-- tests/cases/standalone/common/tql-explain-analyze/)
CREATE TABLE m (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, val DOUBLE);

INSERT INTO m VALUES (0, 'a', 1.0), (10000, 'a', 2.0), (0, 'b', 5.0);

TQL EVAL (0, 10, '10s') m;
----
ts|value|__name__|host
0|1.0|m|a
0|5.0|m|b
10000|2.0|m|a
10000|5.0|m|b

TQL EVAL (0, 10, '10s') sum(m);
----
ts|value
0|6.0
10000|7.0
