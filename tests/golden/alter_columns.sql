-- ALTER TABLE add/drop columns (common/alter)

CREATE TABLE al (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO al (ts, host, v) VALUES (1000, 'a', 1.5);

ALTER TABLE al ADD COLUMN mem DOUBLE;

INSERT INTO al (ts, host, v, mem) VALUES (2000, 'a', 2.5, 90.0);

SELECT ts, v, mem FROM al ORDER BY ts;
----
ts|v|mem
1000|1.5|NULL
2000|2.5|90.0

ALTER TABLE al ADD COLUMN dc STRING;

SELECT ts, dc FROM al ORDER BY ts;
----
ts|dc
1000|NULL
2000|NULL

ALTER TABLE al DROP COLUMN mem;

DESCRIBE al;
----
Column|Type|Key|Null|Default|Semantic Type
ts|TIMESTAMP(3)|PRI|NO||TIMESTAMP
host|STRING|PRI|NO||TAG
v|DOUBLE||YES||FIELD
dc|STRING||YES||FIELD

ALTER TABLE al DROP COLUMN ts;
----
ERROR

ALTER TABLE al DROP COLUMN host;
----
ERROR

DROP TABLE al;

