-- geo + network scalar functions (reference: scalars/geo/, scalars/ip)
SELECT h3_latlng_to_cell(37.7749, -122.4194, 8) IS NOT NULL;
----
h3_latlng_to_cell(37.7749, -122.4194, 8) IS NOT NULL
true

SELECT round(st_distance_sphere_m(37.7749, -122.4194, 34.0522, -118.2437) / 1000.0, 0);
----
round(st_distance_sphere_m(37.7749, -122.4194, 34.0522, -118.2437) / 1000.0, 0)
559.0

SELECT ipv4_string_to_num('10.0.0.1');
----
ipv4_string_to_num('10.0.0.1')
167772161

SELECT ipv4_num_to_string(167772161);
----
ipv4_num_to_string(167772161)
10.0.0.1

SELECT ipv4_in_range('10.0.0.7', '10.0.0.0/24'), ipv4_in_range('10.0.1.7', '10.0.0.0/24');
----
ipv4_in_range('10.0.0.7', '10.0.0.0/24')|ipv4_in_range('10.0.1.7', '10.0.0.0/24')
true|false
