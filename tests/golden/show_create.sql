-- SHOW CREATE TABLE fidelity (common/show/show_create.sql)

CREATE TABLE scr (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE NOT NULL, note STRING DEFAULT 'info', n BIGINT DEFAULT 7) WITH (ttl = '1h');

SHOW CREATE TABLE scr;
----
Table|Create Table
scr|CREATE TABLE IF NOT EXISTS `scr` (
  `ts` TIMESTAMP(3) NOT NULL,
  `host` STRING NOT NULL,
  `v` DOUBLE NOT NULL,
  `note` STRING DEFAULT 'info',
  `n` BIGINT DEFAULT 7,
  TIME INDEX (`ts`),
  PRIMARY KEY (`host`)
)
ENGINE=mito
WITH('ttl'='1h')

DROP TABLE scr;

CREATE TABLE scr2 (ts TIMESTAMP TIME INDEX, a STRING, b STRING, v DOUBLE, PRIMARY KEY (a, b));

SHOW CREATE TABLE scr2;
----
Table|Create Table
scr2|CREATE TABLE IF NOT EXISTS `scr2` (
  `ts` TIMESTAMP(3) NOT NULL,
  `a` STRING NOT NULL,
  `b` STRING NOT NULL,
  `v` DOUBLE,
  TIME INDEX (`ts`),
  PRIMARY KEY (`a`, `b`)
)
ENGINE=mito

DROP TABLE scr2;

