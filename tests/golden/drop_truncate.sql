-- DROP / TRUNCATE semantics (common/drop, common/truncate)

CREATE TABLE dt (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO dt (ts, v) VALUES (1000, 1), (2000, 2);

SELECT count(*) FROM dt;
----
count(*)
2

TRUNCATE TABLE dt;

SELECT count(*) FROM dt;
----
count(*)
0

INSERT INTO dt (ts, v) VALUES (3000, 3);

SELECT v FROM dt;
----
v
3.0

DROP TABLE dt;

DROP TABLE dt;
----
ERROR

DROP TABLE IF EXISTS dt;

SELECT count(*) FROM dt;
----
ERROR

