-- SHOW statements (common/show)

CREATE DATABASE showdb;

CREATE TABLE st1 (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE DEFAULT 7);

SHOW TABLES;
----
Tables
st1

SHOW TABLES LIKE 'st%';
----
Tables
st1

SHOW DATABASES LIKE 'show%';
----
Database
showdb

SHOW COLUMNS FROM st1;
----
Column|Type|Null|Key|Default
ts|timestamp_ms|No|TIME INDEX|
host|string|No|PRI|
v|float64|Yes||7

SHOW INDEX FROM st1;
----
Table|Key_name|Seq_in_index|Column_name
st1|PRIMARY|1|host
st1|TIME INDEX|1|ts

DROP TABLE st1;

DROP DATABASE showdb;

