-- TQL subqueries + offset (promql/)

CREATE TABLE sq (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE);

INSERT INTO sq (ts, host, greptime_value) VALUES
  (0, 'a', 0), (30000, 'a', 30), (60000, 'a', 60), (90000, 'a', 90), (120000, 'a', 120);

TQL EVAL (120, 120, '30s') sq offset 1m;
----
ts|value|__name__|host
120000|60.0|sq|a

TQL EVAL (120, 120, '30s') max_over_time(sq[1m:30s]);
----
ts|value|host
120000|120.0|a

TQL EVAL (120, 120, '30s') avg_over_time(rate(sq[1m])[1m:30s]);
----
ts|value|host
120000|1.0|a

DROP TABLE sq;

