-- TQL subqueries and offset modifiers
CREATE TABLE sq (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, val DOUBLE);

INSERT INTO sq VALUES (0, 'a', 1.0), (30000, 'a', 4.0), (60000, 'a', 9.0);

TQL EVAL (60, 60, '30s') max_over_time(sq[1m:30s]);
----
ts|value|host
60000|9.0|a

TQL EVAL (60, 60, '30s') sq offset 30s;
----
ts|value|__name__|host
60000|4.0|sq|a

TQL EVAL (60, 60, '30s') avg_over_time(sq[1m]);
----
ts|value|host
60000|6.5|a

DROP TABLE sq;
