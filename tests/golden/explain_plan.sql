-- EXPLAIN output shape (common/tql-explain-analyze, EXPLAIN SELECT)

CREATE TABLE ex (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO ex (ts, host, v) VALUES (1000, 'a', 1);

EXPLAIN SELECT host, sum(v) FROM ex WHERE ts > 0 GROUP BY host;
----
plan
SelectPlan[aggregate] table=ex
  Scan: ts=[1, None] matchers=[] residual=None
  Aggregate: keys=['host'] aggs=['sum(v)']

DROP TABLE ex;

