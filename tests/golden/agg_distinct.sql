-- DISTINCT aggregates + SELECT DISTINCT (common/aggregate/distinct.sql)

CREATE TABLE d (host STRING, v BIGINT, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host));

INSERT INTO d (host, v, ts) VALUES ('a', 1, 1000), ('a', 1, 2000), ('b', 2, 1000), ('b', 3, 2000), ('c', 1, 1000);

SELECT count(DISTINCT host) FROM d;
----
count(DISTINCT host)
3

SELECT count(DISTINCT v) FROM d;
----
count(DISTINCT v)
3

SELECT DISTINCT v FROM d ORDER BY v;
----
v
1
2
3

SELECT DISTINCT host, v FROM d ORDER BY host, v;
----
host|v
a|1
b|2
b|3
c|1

SELECT host, count(DISTINCT v) FROM d GROUP BY host ORDER BY host;
----
host|count(DISTINCT v)
a|1
b|2
c|1

DROP TABLE d;

