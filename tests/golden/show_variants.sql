-- SHOW family variants with LIKE/WHERE filters
CREATE TABLE alpha (ts TIMESTAMP TIME INDEX, v DOUBLE);

CREATE TABLE beta (ts TIMESTAMP TIME INDEX, v DOUBLE);

SHOW TABLES;
----
Tables
alpha
beta

SHOW TABLES LIKE 'al%';
----
Tables
alpha

SHOW DATABASES;
----
Database
public

SHOW FULL TABLES;
----
Tables
alpha
beta

DROP TABLE alpha;

DROP TABLE beta;
