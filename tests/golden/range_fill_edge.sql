-- RANGE FILL edges: prev/linear/const/null across gaps, per-item override
CREATE TABLE rf (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO rf VALUES (0, 'a', 1.0), (30000, 'a', 4.0), (0, 'b', 10.0), (10000, 'b', 20.0);

SELECT ts, host, avg(v) RANGE '10s' FROM rf ALIGN '10s' ORDER BY host, ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
30000|a|4.0
0|b|10.0
10000|b|20.0

SELECT ts, host, avg(v) RANGE '10s' FILL PREV FROM rf ALIGN '10s' ORDER BY host, ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|1.0
20000|a|1.0
30000|a|4.0
0|b|10.0
10000|b|20.0
20000|b|20.0
30000|b|20.0

SELECT ts, host, avg(v) RANGE '10s' FILL LINEAR FROM rf ALIGN '10s' ORDER BY host, ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|2.0
20000|a|3.0
30000|a|4.0
0|b|10.0
10000|b|20.0
20000|b|20.0
30000|b|20.0

SELECT ts, host, avg(v) RANGE '10s' FILL 6.28 FROM rf ALIGN '10s' ORDER BY host, ts;
----
ts|host|avg(v) RANGE 10000ms
0|a|1.0
10000|a|6.28
20000|a|6.28
30000|a|4.0
0|b|10.0
10000|b|20.0
20000|b|6.28
30000|b|6.28

SELECT ts, host, max(v) RANGE '10s' FILL PREV, min(v) RANGE '10s' FILL NULL FROM rf ALIGN '10s' ORDER BY host, ts;
----
ts|host|max(v) RANGE 10000ms|min(v) RANGE 10000ms
0|a|1.0|1.0
10000|a|1.0|NULL
20000|a|1.0|NULL
30000|a|4.0|4.0
0|b|10.0|10.0
10000|b|20.0|20.0
20000|b|20.0|NULL
30000|b|20.0|NULL

DROP TABLE rf;
