-- metric engine: many logical tables multiplexed over one physical
-- region pair (reference: src/metric-engine/)
CREATE TABLE phys (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE) WITH (physical_metric_table = 'true');

CREATE TABLE api_requests (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE) WITH (on_physical_table = 'phys');

CREATE TABLE api_errors (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, greptime_value DOUBLE) WITH (on_physical_table = 'phys');

INSERT INTO api_requests VALUES (1000, 'a', 100.0), (2000, 'b', 200.0);

INSERT INTO api_errors VALUES (1000, 'a', 3.0);

SELECT host, greptime_value FROM api_requests ORDER BY host;
----
host|greptime_value
a|100.0
b|200.0

SELECT host, greptime_value FROM api_errors ORDER BY host;
----
host|greptime_value
a|3.0

SELECT count(*) FROM api_requests;
----
count(*)
2

DROP TABLE api_requests;

DROP TABLE api_errors;

DROP TABLE phys;
