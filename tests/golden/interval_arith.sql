-- INTERVAL arithmetic with timestamps (reference: common/types/interval/)
CREATE TABLE ia (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO ia VALUES (3600000, 1.0), (7200000, 2.0);

SELECT ts + INTERVAL '1 hour' FROM ia ORDER BY ts;
----
ts + INTERVAL '1 hour'
7200000
10800000

SELECT ts - INTERVAL '30 minutes' FROM ia ORDER BY ts;
----
ts - INTERVAL '30 minutes'
1800000
5400000

SELECT v FROM ia WHERE ts > INTERVAL '30 minutes' + 1800000 ORDER BY ts;
----
v
2.0

SELECT INTERVAL '1 day' + INTERVAL '2 hours';
----
INTERVAL '1 day' + INTERVAL '2 hours'
93600000

DROP TABLE ia;
