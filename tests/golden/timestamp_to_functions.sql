-- timestamp constructors and conversions
SELECT to_timestamp(1705329015);
----
to_timestamp(1705329015)
1705329015000

SELECT to_timestamp_millis(1705329015123);
----
to_timestamp_millis(1705329015123)
1705329015123

SELECT greatest(1, 2, 3), least(4.5, 2.5);
----
greatest(1, 2, 3)|least(4.5, 2.5)
3.0|2.5

SELECT now() > to_timestamp(0);
----
now() > to_timestamp(0)
true

SELECT date_add(to_timestamp_millis(0), INTERVAL '1 day');
----
date_add(to_timestamp_millis(0), INTERVAL '1 day')
86400000

SELECT date_sub(to_timestamp_millis(86400000), INTERVAL '12 hours');
----
date_sub(to_timestamp_millis(86400000), INTERVAL '12 hours')
43200000
