-- LIMIT/OFFSET edges: zero, beyond-end, with aggregates and distinct
CREATE TABLE lo (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, v DOUBLE);

INSERT INTO lo VALUES (1000, 'a', 1.0), (2000, 'b', 2.0), (3000, 'c', 3.0);

SELECT g FROM lo ORDER BY g LIMIT 0;
----
g

SELECT g FROM lo ORDER BY g LIMIT 10;
----
g
a
b
c

SELECT g FROM lo ORDER BY g OFFSET 2;
----
g
c

SELECT g FROM lo ORDER BY g LIMIT 1 OFFSET 5;
----
g

SELECT g, sum(v) FROM lo GROUP BY g ORDER BY g LIMIT 2;
----
g|sum(v)
a|1.0
b|2.0

SELECT DISTINCT g FROM lo ORDER BY g DESC LIMIT 2;
----
g
c
b

DROP TABLE lo;
