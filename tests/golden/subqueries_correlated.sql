-- Correlated subqueries: EXISTS / NOT EXISTS / IN / scalar (reference

CREATE TABLE orders (ts TIMESTAMP TIME INDEX, cust STRING PRIMARY KEY, amount DOUBLE);

INSERT INTO orders (ts, cust, amount) VALUES (1000, 'a', 10), (2000, 'a', 20), (1000, 'b', 5), (3000, 'c', 50);

CREATE TABLE vip (ts TIMESTAMP TIME INDEX, name STRING PRIMARY KEY, tier BIGINT);

INSERT INTO vip (ts, name, tier) VALUES (1000, 'a', 1), (1000, 'c', 2);

SELECT cust, amount FROM orders o WHERE EXISTS (SELECT 1 FROM vip v WHERE v.name = o.cust) ORDER BY cust, amount;
----
cust|amount
a|10.0
a|20.0
c|50.0

SELECT cust FROM orders o WHERE NOT EXISTS (SELECT 1 FROM vip v WHERE v.name = o.cust) ORDER BY cust;
----
cust
b

SELECT cust FROM orders o WHERE EXISTS (SELECT 1 FROM vip v WHERE v.name = o.cust AND v.tier >= 2) ORDER BY cust;
----
cust
c

SELECT cust, amount, (SELECT max(tier) FROM vip v WHERE v.name = o.cust) AS t FROM orders o ORDER BY cust, amount;
----
cust|amount|t
a|10.0|1
a|20.0|1
b|5.0|NULL
c|50.0|2

SELECT cust, (SELECT count(*) FROM vip v WHERE v.name = o.cust) AS n FROM orders o WHERE amount > 15 ORDER BY cust;
----
cust|n
a|1
c|1

SELECT cust, amount FROM orders o WHERE amount IN (SELECT tier * 10 FROM vip v WHERE v.name = o.cust) ORDER BY cust;
----
cust|amount
a|10.0

SELECT cust, amount FROM orders o WHERE amount NOT IN (SELECT tier * 10 FROM vip v WHERE v.name = o.cust) ORDER BY cust, amount;
----
cust|amount
a|20.0
b|5.0
c|50.0

SELECT cust, sum(amount) AS s FROM orders o WHERE EXISTS (SELECT 1 FROM vip v WHERE v.name = o.cust) GROUP BY cust ORDER BY cust;
----
cust|s
a|30.0
c|50.0

SELECT o.cust, (SELECT sum(amount) FROM orders o2 WHERE o2.cust = o.cust) AS total FROM orders o WHERE o.ts = 1000 ORDER BY o.cust;
----
cust|total
a|30.0
b|5.0

DROP TABLE orders;

DROP TABLE vip;

