-- session time-zone variable round-trips through SET / SHOW VARIABLES
SET time_zone = '+05:00';

SHOW VARIABLES LIKE 'time_zone';
----
Variable_name|Value
time_zone|+05:00

SET time_zone = 'UTC';

SHOW VARIABLES LIKE 'time_zone';
----
Variable_name|Value
time_zone|UTC

SET SESSION read_preference = 'leader';

SHOW VARIABLES LIKE 'read_preference';
----
Variable_name|Value
read_preference|leader
