-- session time zone affects rendering, storage stays UTC ms
CREATE TABLE tz (ts TIMESTAMP TIME INDEX, v DOUBLE);

INSERT INTO tz VALUES (0, 1.0);

SET time_zone = '+05:00';

SELECT @@time_zone;
----
ERROR <<InvalidSyntaxError: unexpected token '@' at 7>>

SET time_zone = 'UTC';

SELECT @@time_zone;
----
ERROR <<InvalidSyntaxError: unexpected token '@' at 7>>

DROP TABLE tz;
