-- string min/max aggregates (lexicographic, typed output)
CREATE TABLE sm (ts TIMESTAMP TIME INDEX, g STRING PRIMARY KEY, name STRING);

INSERT INTO sm VALUES (1000, 'x', 'zebra'), (2000, 'x', 'ant'), (3000, 'y', 'mole');

SELECT g, min(name), max(name) FROM sm GROUP BY g ORDER BY g;
----
g|min(name)|max(name)
x|ant|zebra
y|mole|mole

SELECT min(name) FROM sm;
----
min(name)
ant

DROP TABLE sm;
