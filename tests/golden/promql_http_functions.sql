-- TQL scalar functions over instant vectors
CREATE TABLE pf (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, val DOUBLE);

INSERT INTO pf VALUES (0, 'a', -2.5), (0, 'b', 7.9);

TQL EVAL (0, 0, '10s') abs(pf);
----
ts|value|host
0|2.5|a
0|7.9|b

TQL EVAL (0, 0, '10s') ceil(pf);
----
ts|value|host
0|-2.0|a
0|8.0|b

TQL EVAL (0, 0, '10s') floor(pf);
----
ts|value|host
0|-3.0|a
0|7.0|b

TQL EVAL (0, 0, '10s') clamp(pf, 0, 5);
----
ts|value|host
0|0.0|a
0|5.0|b

TQL EVAL (0, 0, '10s') sgn(pf);
----
ts|value|host
0|-1.0|a
0|1.0|b

DROP TABLE pf;
