-- DESCRIBE / SHOW FULL / information_schema columns (common/describe)

CREATE TABLE dsm (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE NOT NULL, note STRING DEFAULT 'x');

DESCRIBE dsm;
----
Column|Type|Key|Null|Default|Semantic Type
ts|TIMESTAMP(3)|PRI|NO||TIMESTAMP
host|STRING|PRI|NO||TAG
v|DOUBLE||NO||FIELD
note|STRING||YES|x|FIELD

SHOW FULL COLUMNS FROM dsm;
----
Column|Type|Null|Key|Default|Semantic Type
ts|timestamp_ms|No|TIME INDEX||TIMESTAMP
host|string|No|PRI||TAG
v|float64|No|||FIELD
note|string|Yes||x|FIELD

SELECT column_name, data_type, semantic_type FROM information_schema.columns WHERE table_name = 'dsm' ORDER BY column_name;
----
column_name|data_type|semantic_type
host|string|TAG
note|string|FIELD
ts|timestamp_ms|TIMESTAMP
v|float64|FIELD

SELECT table_name, table_type FROM information_schema.tables WHERE table_name = 'dsm';
----
table_name|table_type
dsm|BASE TABLE

DROP TABLE dsm;

