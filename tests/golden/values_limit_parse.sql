-- parser edge cases: quoted identifiers, comments, negative literals

CREATE TABLE pe (ts TIMESTAMP TIME INDEX, "select" DOUBLE, v DOUBLE);

INSERT INTO pe (ts, "select", v) VALUES (1000, -1.5, 2e3);

SELECT "select", v FROM pe;
----
select|v
-1.5|2000.0

SELECT v FROM pe WHERE v = 2000.0;
----
v
2000.0

SELECT 1 + /* inline */ 2;
----
1 + 2
3

SELECT 'it''s quoted';
----
'it''s quoted'
it's quoted

DROP TABLE pe;

