-- Aggregates (reference sqlness: common/aggregate/)
CREATE TABLE m (host STRING, region STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY(host, region));

INSERT INTO m (host, region, v, ts) VALUES
  ('h1', 'us', 1, 1000), ('h1', 'us', 3, 2000),
  ('h2', 'us', 5, 1000), ('h2', 'eu', 7, 2000),
  ('h3', 'eu', 9, 1000);

SELECT count(*) FROM m;
----
count(*)
5

SELECT region, count(*) AS n, sum(v) AS s, avg(v) AS a FROM m GROUP BY region ORDER BY region;
----
region|n|s|a
eu|2|16.0|8.0
us|3|9.0|3.0

SELECT region, min(v) AS lo, max(v) AS hi FROM m GROUP BY region ORDER BY region;
----
region|lo|hi
eu|7.0|9.0
us|1.0|5.0

SELECT host, count(DISTINCT region) AS r FROM m GROUP BY host ORDER BY host;
----
host|r
h1|1
h2|2
h3|1

SELECT region, sum(v) AS s FROM m GROUP BY region HAVING sum(v) > 10 ORDER BY region;
----
region|s
eu|16.0

SELECT DISTINCT region FROM m ORDER BY region;
----
region
eu
us

SELECT region, last_value(v ORDER BY ts) AS lv FROM m GROUP BY region ORDER BY region;
----
region|lv
eu|7.0
us|3.0

-- grouped expression keys (all values are odd: one group)
SELECT v % 2 AS parity, count(*) AS n FROM m GROUP BY v % 2 ORDER BY parity;
----
parity|n
1.0|5

SELECT floor(v / 4) AS bucket, count(*) AS n FROM m GROUP BY floor(v / 4) ORDER BY bucket;
----
bucket|n
0.0|2
1.0|2
2.0|1

-- aggregate over empty input: one row, count 0
SELECT count(*) AS n, sum(v) AS s FROM m WHERE v > 100;
----
n|s
0|NULL
