-- CAST between types (common/types + select/cast)

SELECT CAST(1.9 AS BIGINT);
----
CAST(1.9 AS int64)
1

SELECT CAST('42' AS BIGINT);
----
CAST('42' AS int64)
42

SELECT CAST(42 AS DOUBLE);
----
CAST(42 AS float64)
42.0

SELECT CAST('3.5' AS DOUBLE) * 2;
----
CAST('3.5' AS float64) * 2
7.0

SELECT CAST(1 AS BOOLEAN);
----
CAST(1 AS bool)
true

SELECT CAST('1970-01-01 00:00:01' AS TIMESTAMP);
----
CAST('1970-01-01 00:00:01' AS timestamp_ms)
1000

SELECT CAST(2.5 AS STRING);
----
CAST(2.5 AS string)
2.5

