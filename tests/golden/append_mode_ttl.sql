-- table WITH options: append_mode, merge_mode (common/create + mito)

CREATE TABLE ap (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE) WITH (append_mode = 'true');

INSERT INTO ap (ts, host, v) VALUES (1000, 'a', 1.0);

INSERT INTO ap (ts, host, v) VALUES (1000, 'a', 2.0);

SELECT count(*) FROM ap;
----
count(*)
2

CREATE TABLE lww (ts TIMESTAMP TIME INDEX, host STRING PRIMARY KEY, v DOUBLE);

INSERT INTO lww (ts, host, v) VALUES (1000, 'a', 1.0);

INSERT INTO lww (ts, host, v) VALUES (1000, 'a', 2.0);

SELECT v FROM lww;
----
v
2.0

DROP TABLE ap;

DROP TABLE lww;

