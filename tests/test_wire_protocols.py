"""MySQL wire protocol + Arrow Flight frontends (VERDICT r2 task #7).

The MySQL test client speaks the real 4.1 protocol over a socket — the
same bytes a mysql CLI or connector sends.
"""

import socket
import struct

import numpy as np
import pyarrow as pa
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.mysql import MySqlServer, native_password_token

flight = pytest.importorskip("pyarrow.flight")

CLIENT_PROTOCOL_41 = 0x0200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x08


class MiniMySqlClient:
    """Just enough client protocol for the tests: handshake + COM_QUERY."""

    def __init__(self, port, user="root", password="", db=None):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.seq = 0
        greeting = self._read_packet()
        assert greeting[0] == 0x0A, "expected protocol 10 greeting"
        i = greeting.index(b"\x00", 1) + 1   # server version
        i += 4                               # thread id
        auth1 = greeting[i:i + 8]
        i += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10
        auth2 = greeting[i:i + 12]
        scramble = auth1 + auth2
        caps = (CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION
                | CLIENT_PLUGIN_AUTH)
        if db:
            caps |= CLIENT_CONNECT_WITH_DB
        token = native_password_token(password, scramble)
        resp = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        resp += bytes([255]) + b"\x00" * 23
        resp += user.encode() + b"\x00"
        resp += bytes([len(token)]) + token
        if db:
            resp += db.encode() + b"\x00"
        resp += b"mysql_native_password\x00"
        self._send_packet(resp)
        ok = self._read_packet()
        if ok[0] == 0xFF:
            code = struct.unpack("<H", ok[1:3])[0]
            raise PermissionError(f"auth failed: {code}")
        assert ok[0] == 0x00

    def _read_packet(self):
        head = self._read_n(4)
        ln = head[0] | (head[1] << 8) | (head[2] << 16)
        self.seq = head[3] + 1
        return self._read_n(ln) if ln else b""

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("eof")
            buf += chunk
        return buf

    def _send_packet(self, payload):
        ln = len(payload)
        self.sock.sendall(bytes([
            ln & 0xFF, (ln >> 8) & 0xFF, (ln >> 16) & 0xFF, self.seq & 0xFF
        ]) + payload)
        self.seq += 1

    @staticmethod
    def _lenc(data, i):
        b0 = data[i]
        if b0 < 0xFB:
            return b0, i + 1
        if b0 == 0xFC:
            return struct.unpack("<H", data[i + 1:i + 3])[0], i + 3
        if b0 == 0xFD:
            return int.from_bytes(data[i + 1:i + 4], "little"), i + 4
        return struct.unpack("<Q", data[i + 1:i + 9])[0], i + 9

    def query(self, sql: str):
        """Returns (column_names, rows) or raises on ERR."""
        self.seq = 0
        self._send_packet(b"\x03" + sql.encode())
        first = self._read_packet()
        if first[0] == 0xFF:
            raise RuntimeError(first[9:].decode("utf-8", "replace"))
        if first[0] == 0x00:
            return [], []  # OK packet (no resultset)
        ncols, _ = self._lenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._read_packet()
            i = 0
            vals = []
            for _ in range(5):
                ln, i = self._lenc(col, i)
                vals.append(col[i:i + ln])
                i += ln
            names.append(vals[4].decode())
        eof = self._read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            row = []
            i = 0
            while i < len(pkt):
                if pkt[i] == 0xFB:
                    row.append(None)
                    i += 1
                else:
                    ln, i = self._lenc(pkt, i)
                    row.append(pkt[i:i + ln].decode())
                    i += ln
            rows.append(row)
        return names, rows

    # ---- binary prepared statements (COM_STMT_*) ---------------------
    def stmt_prepare(self, sql: str) -> tuple[int, int]:
        """-> (stmt_id, n_params)"""
        self.seq = 0
        self._send_packet(b"\x16" + sql.encode())
        ok = self._read_packet()
        if ok[0] == 0xFF:
            raise RuntimeError(ok[9:].decode("utf-8", "replace"))
        stmt_id = struct.unpack("<I", ok[1:5])[0]
        ncols = struct.unpack("<H", ok[5:7])[0]
        nparams = struct.unpack("<H", ok[7:9])[0]
        for _ in range(nparams):
            self._read_packet()
        if nparams:
            assert self._read_packet()[0] == 0xFE
        for _ in range(ncols):
            self._read_packet()
        if ncols:
            assert self._read_packet()[0] == 0xFE
        return stmt_id, nparams

    def stmt_execute(self, stmt_id: int, args: list, *, rebind=True):
        """Binary execute; args typed as double/longlong/string/NULL.
        Returns (names, rows) with rows as decoded strings."""
        payload = b"\x17" + struct.pack("<I", stmt_id) + b"\x00"
        payload += struct.pack("<I", 1)
        if args:
            nb = (len(args) + 7) // 8
            bitmap = bytearray(nb)
            types = b""
            values = b""
            for k, a in enumerate(args):
                if a is None:
                    bitmap[k // 8] |= 1 << (k % 8)
                    types += bytes([0x06, 0])
                elif isinstance(a, float):
                    types += bytes([0x05, 0])
                    values += struct.pack("<d", a)
                elif isinstance(a, int):
                    types += bytes([0x08, 0])
                    values += struct.pack("<q", a)
                else:
                    s = str(a).encode()
                    types += bytes([0xFD, 0])
                    assert len(s) < 0xFB
                    values += bytes([len(s)]) + s
            if rebind:
                payload += bytes(bitmap) + b"\x01" + types + values
            else:
                payload += bytes(bitmap) + b"\x00" + values
        self.seq = 0
        self._send_packet(payload)
        first = self._read_packet()
        if first[0] == 0xFF:
            raise RuntimeError(first[9:].decode("utf-8", "replace"))
        if first[0] == 0x00:
            return [], []  # OK packet (a column count is never 0)
        ncols, _ = self._lenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._read_packet()
            i = 0
            vals = []
            for _ in range(5):
                ln, i = self._lenc(col, i)
                vals.append(col[i:i + ln])
                i += ln
            names.append(vals[4].decode())
        assert self._read_packet()[0] == 0xFE
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            assert pkt[0] == 0x00
            nb = (ncols + 7 + 2) // 8
            bitmap = pkt[1:1 + nb]
            i = 1 + nb
            row = []
            for c in range(ncols):
                pos = c + 2
                if bitmap[pos // 8] & (1 << (pos % 8)):
                    row.append(None)
                    continue
                ln, i = self._lenc(pkt, i)
                row.append(pkt[i:i + ln].decode())
                i += ln
            rows.append(row)
        return names, rows

    def stmt_close(self, stmt_id: int):
        self.seq = 0
        self._send_packet(b"\x19" + struct.pack("<I", stmt_id))

    def close(self):
        try:
            self.seq = 0
            self._send_packet(b"\x01")
        except OSError:
            pass
        self.sock.close()


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    s.sql(
        "CREATE TABLE wt (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    s.sql(
        "INSERT INTO wt (host, v, ts) VALUES ('a', 1.5, 1000), "
        "('b', 2.5, 2000)"
    )
    yield s
    s.close()


def test_mysql_query_roundtrip(inst):
    srv = MySqlServer(inst, port=0).start()
    try:
        c = MiniMySqlClient(srv.port)
        names, rows = c.query("SELECT host, v FROM wt ORDER BY host")
        assert names == ["host", "v"]
        assert rows == [["a", "1.5"], ["b", "2.5"]]
        # connect-time probe
        names, rows = c.query("select @@version_comment limit 1")
        assert rows == [["GreptimeDB-TPU"]]
        # SET routes through the engine; @@ probes read the value back
        c.query("SET time_zone = '+08:00'")
        names, rows = c.query("select @@time_zone")
        assert rows == [["+08:00"]]
        # DDL/insert through the wire
        names, rows = c.query(
            "INSERT INTO wt (host, v, ts) VALUES ('c', 9.0, 3000)"
        )
        names, rows = c.query("SELECT count(*) FROM wt")
        assert rows == [["3"]]
        # error surfaces as ERR packet
        with pytest.raises(RuntimeError):
            c.query("SELECT nope FROM missing_table")
        c.close()
    finally:
        srv.close()


def test_mysql_binary_prepared_statements(inst):
    srv = MySqlServer(inst, port=0).start()
    try:
        c = MiniMySqlClient(srv.port)
        sid, nparams = c.stmt_prepare(
            "SELECT host, v FROM wt WHERE v > ? ORDER BY host"
        )
        assert nparams == 1
        names, rows = c.stmt_execute(sid, [2.0])
        assert names == ["host", "v"]
        assert rows == [["b", "2.5"]]
        # re-execute with different binding
        _, rows = c.stmt_execute(sid, [0.0])
        assert [r[0] for r in rows] == ["a", "b"]
        # string + int params, insert through binary protocol
        sid2, n2 = c.stmt_prepare(
            "INSERT INTO wt (host, v, ts) VALUES (?, ?, ?)"
        )
        assert n2 == 3
        assert c.stmt_execute(sid2, ["z", 7.5, 9000]) == ([], [])
        _, rows = c.stmt_execute(sid, [7.0])
        assert rows == [["z", "7.5"]]
        # NULL binding round-trips
        sid3, _ = c.stmt_prepare("SELECT ? IS NULL")
        _, rows = c.stmt_execute(sid3, [None])
        assert rows[0][0] in ("1", "true", "True")
        # libmysqlclient sends types only on the FIRST execute
        # (new_params_bind_flag=0 afterwards)
        _, rows = c.stmt_execute(sid, [7.0], rebind=False)
        assert rows == [["z", "7.5"]]
        c.stmt_close(sid)
        with pytest.raises(RuntimeError):
            c.stmt_execute(sid, [1.0])
        c.close()
    finally:
        srv.close()


def test_mysql_auth(inst):
    from greptimedb_tpu.auth import StaticUserProvider

    provider = StaticUserProvider({"alice": "secret"})
    srv = MySqlServer(inst, port=0, user_provider=provider).start()
    try:
        c = MiniMySqlClient(srv.port, user="alice", password="secret")
        _, rows = c.query("SELECT 1")
        assert rows == [["1"]]
        c.close()
        with pytest.raises(PermissionError):
            MiniMySqlClient(srv.port, user="alice", password="wrong")
        with pytest.raises(PermissionError):
            MiniMySqlClient(srv.port, user="mallory", password="secret")
    finally:
        srv.close()


def test_mysql_init_db(inst):
    inst.sql("CREATE DATABASE mdb")
    inst.sql(
        "CREATE TABLE mdb.t2 (v DOUBLE, ts TIMESTAMP TIME INDEX)"
    )
    inst.sql("INSERT INTO mdb.t2 (v, ts) VALUES (7.0, 1000)")
    srv = MySqlServer(inst, port=0).start()
    try:
        c = MiniMySqlClient(srv.port, db="mdb")
        _, rows = c.query("SELECT v FROM t2")
        assert rows == [["7.0"]]
        c.close()
    finally:
        srv.close()


def test_flight_do_get_and_info(inst):
    from greptimedb_tpu.servers.flight import FlightFrontend

    f = FlightFrontend(inst, port=0).start()
    try:
        client = flight.connect(f"grpc://127.0.0.1:{f.server.port}")
        reader = client.do_get(
            flight.Ticket(b"SELECT host, v, ts FROM wt ORDER BY host")
        )
        table = reader.read_all()
        assert table.column("host").to_pylist() == ["a", "b"]
        assert table.column("v").to_pylist() == [1.5, 2.5]
        assert pa.types.is_timestamp(table.schema.field("ts").type)
        info = client.get_flight_info(
            flight.FlightDescriptor.for_command(b"SELECT count(*) FROM wt")
        )
        assert info.total_records == 1
        with pytest.raises(flight.FlightServerError):
            client.do_get(flight.Ticket(b"SELECT broken FROM nothing"))
    finally:
        f.close()


def test_flight_do_put_ingest(inst):
    from greptimedb_tpu.servers.flight import FlightFrontend

    f = FlightFrontend(inst, port=0).start()
    try:
        client = flight.connect(f"grpc://127.0.0.1:{f.server.port}")
        batch = pa.record_batch({
            "host": pa.array(["c", "d"]),
            "v": pa.array([10.0, 20.0]),
            "ts": pa.array(
                np.asarray([4000, 5000], np.int64), pa.timestamp("ms")
            ),
        })
        desc = flight.FlightDescriptor.for_path("wt")
        writer, _ = client.do_put(desc, batch.schema)
        writer.write_batch(batch)
        writer.close()
        res = inst.sql("SELECT host, v FROM wt ORDER BY host")
        rows = [list(r) for r in res.rows()]
        assert rows == [
            ["a", 1.5], ["b", 2.5], ["c", 10.0], ["d", 20.0],
        ]
    finally:
        f.close()


def test_flight_auth(inst):
    from greptimedb_tpu.auth import StaticUserProvider
    from greptimedb_tpu.servers.flight import FlightFrontend

    provider = StaticUserProvider({"alice": "secret"})
    f = FlightFrontend(inst, port=0, user_provider=provider).start()
    try:
        client = flight.connect(f"grpc://127.0.0.1:{f.server.port}")
        with pytest.raises(flight.FlightUnauthenticatedError):
            client.do_get(flight.Ticket(b"SELECT 1")).read_all()
        token = client.authenticate_basic_token("alice", "secret")
        opts = flight.FlightCallOptions(headers=[token])
        t = client.do_get(
            flight.Ticket(b"SELECT count(*) FROM wt"), options=opts
        )
        assert t.read_all().num_rows == 1
        bad = flight.connect(f"grpc://127.0.0.1:{f.server.port}")
        with pytest.raises(flight.FlightUnauthenticatedError):
            bad.authenticate_basic_token("alice", "wrong")
    finally:
        f.close()


def test_mysql_unknown_database_rejected(inst):
    srv = MySqlServer(inst, port=0).start()
    try:
        c = MiniMySqlClient(srv.port)
        c.seq = 0
        c._send_packet(b"\x02nodb")
        err = c._read_packet()
        assert err[0] == 0xFF
        assert struct.unpack("<H", err[1:3])[0] == 1049
        c.close()
    finally:
        srv.close()


# ----------------------------------------------------------------------
# PostgreSQL wire protocol
# ----------------------------------------------------------------------

class MiniPgClient:
    """Just enough protocol-3 client for the tests: startup + simple and
    extended query, cleartext auth."""

    def __init__(self, port, user="root", password=None, database=None,
                 try_ssl=False):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        if try_ssl:
            self.sock.sendall(struct.pack("!II", 8, 80877103))
            assert self.sock.recv(1) == b"N"
        params = {"user": user}
        if database:
            params["database"] = database
        body = struct.pack("!I", 196608)
        for k, v in params.items():
            body += k.encode() + b"\x00" + v.encode() + b"\x00"
        body += b"\x00"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self.params = {}
        while True:
            tag, payload = self._read_msg()
            if tag == b"R":
                (code,) = struct.unpack("!I", payload[:4])
                if code == 3:
                    if password is None:
                        raise PermissionError("password required")
                    p = password.encode() + b"\x00"
                    self.sock.sendall(
                        b"p" + struct.pack("!I", len(p) + 4) + p
                    )
                elif code != 0:
                    raise AssertionError(f"unexpected auth code {code}")
            elif tag == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            elif tag == b"E":
                raise PermissionError(payload.decode("utf8", "replace"))
            elif tag == b"Z":
                return
            # K (BackendKeyData) ignored

    def _read_msg(self):
        head = b""
        while len(head) < 5:
            chunk = self.sock.recv(5 - len(head))
            assert chunk, "connection closed"
            head += chunk
        (ln,) = struct.unpack("!I", head[1:])
        body = b""
        while len(body) < ln - 4:
            chunk = self.sock.recv(ln - 4 - len(body))
            assert chunk, "connection closed"
            body += chunk
        return head[:1], body

    def query(self, sql):
        p = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(p) + 4) + p)
        return self._collect()

    def extended(self, sql, args):
        def send(tag, payload):
            self.sock.sendall(
                tag + struct.pack("!I", len(payload) + 4) + payload
            )

        send(b"P", b"\x00" + sql.encode() + b"\x00" + struct.pack("!H", 0))
        bind = b"\x00\x00" + struct.pack("!H", 0)
        bind += struct.pack("!H", len(args))
        for a in args:
            if a is None:
                bind += struct.pack("!i", -1)
            else:
                b = str(a).encode()
                bind += struct.pack("!i", len(b)) + b
        bind += struct.pack("!H", 0)
        send(b"B", bind)
        send(b"E", b"\x00" + struct.pack("!I", 0))
        send(b"S", b"")
        return self._collect()

    def _collect(self):
        names, rows, err = [], [], None
        while True:
            tag, body = self._read_msg()
            if tag == b"T":
                (n,) = struct.unpack("!H", body[:2])
                off = 2
                names = []
                for _ in range(n):
                    end = body.index(b"\x00", off)
                    names.append(body[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                (n,) = struct.unpack("!H", body[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", body[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif tag == b"E":
                err = body.decode("utf8", "replace")
            elif tag == b"Z":
                if err:
                    raise RuntimeError(err)
                return names, rows
            # C/1/2/3/n/I ignored

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


def test_postgres_simple_query(inst):
    from greptimedb_tpu.servers.postgres import PostgresServer

    srv = PostgresServer(inst, port=0).start()
    try:
        c = MiniPgClient(srv.port, try_ssl=True)
        assert c.params.get("server_encoding") == "UTF8"
        names, rows = c.query("SELECT host, v FROM wt ORDER BY host")
        assert names == ["host", "v"]
        assert rows == [["a", "1.5"], ["b", "2.5"]]
        c.query("INSERT INTO wt (host, v, ts) VALUES ('pg', 9.0, 9000)")
        _, rows = c.query("SELECT count(*) FROM wt")
        assert rows == [["3"]]
        with pytest.raises(RuntimeError):
            c.query("SELECT broken FROM nothing")
        # connection still usable after an error
        _, rows = c.query("SELECT 1")
        assert rows == [["1"]]
        c.close()
    finally:
        srv.close()


def test_postgres_pg_catalog_introspection(inst):
    """psql-style catalog queries over the PG wire: \\dt's pg_class
    JOIN pg_namespace, pg_type lookups (VERDICT r4 #9)."""
    from greptimedb_tpu.servers.postgres import PostgresServer

    srv = PostgresServer(inst, port=0).start()
    try:
        c = MiniPgClient(srv.port)
        _, rows = c.query(
            "SELECT c.relname FROM pg_catalog.pg_class c "
            "JOIN pg_catalog.pg_namespace n ON n.oid = c.relnamespace "
            "WHERE n.nspname = 'public' AND c.relkind = 'r' "
            "ORDER BY c.relname"
        )
        assert ["wt"] in rows
        _, rows = c.query(
            "SELECT typname FROM pg_catalog.pg_type WHERE oid = 701"
        )
        assert rows == [["float8"]]
        _, rows = c.query("SELECT datname FROM pg_catalog.pg_database")
        assert ["public"] in rows
        c.close()
    finally:
        srv.close()


def test_postgres_extended_protocol(inst):
    from greptimedb_tpu.servers.postgres import PostgresServer

    srv = PostgresServer(inst, port=0).start()
    try:
        c = MiniPgClient(srv.port)
        names, rows = c.extended(
            "SELECT host, v FROM wt WHERE host = $1", ["a"]
        )
        assert rows == [["a", "1.5"]]
        c.close()
    finally:
        srv.close()


def test_postgres_auth(inst):
    from greptimedb_tpu.auth import StaticUserProvider
    from greptimedb_tpu.servers.postgres import PostgresServer

    provider = StaticUserProvider({"alice": "secret"})
    srv = PostgresServer(inst, port=0, user_provider=provider).start()
    try:
        c = MiniPgClient(srv.port, user="alice", password="secret")
        _, rows = c.query("SELECT 1")
        assert rows == [["1"]]
        c.close()
        with pytest.raises(PermissionError):
            MiniPgClient(srv.port, user="alice", password="wrong")
    finally:
        srv.close()


def test_postgres_database_param(inst):
    from greptimedb_tpu.servers.postgres import PostgresServer

    inst.sql("CREATE DATABASE pdb")
    inst.sql("CREATE TABLE pdb.t3 (v DOUBLE, ts TIMESTAMP TIME INDEX)")
    inst.sql("INSERT INTO pdb.t3 (v, ts) VALUES (3.25, 1000)")
    srv = PostgresServer(inst, port=0).start()
    try:
        c = MiniPgClient(srv.port, database="pdb")
        _, rows = c.query("SELECT v FROM t3")
        assert rows == [["3.25"]]
        c.close()
    finally:
        srv.close()
