"""End-to-end SQL tests over the standalone instance (create/insert/query),
modeled on the reference's sqlness golden cases
(/root/reference/tests/cases/standalone/common/)."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


def setup_cpu(inst, rows=None):
    inst.sql(
        "CREATE TABLE cpu (host STRING, region STRING, usage_user DOUBLE, "
        "usage_system DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host, region))"
    )
    if rows is None:
        rows = [
            ("h1", "us-west", 10.0, 1.0, 1000),
            ("h1", "us-west", 20.0, 2.0, 2000),
            ("h2", "us-west", 30.0, 3.0, 1000),
            ("h2", "us-east", 40.0, 4.0, 2000),
            ("h3", "us-east", 50.0, 5.0, 3000),
        ]
    values = ", ".join(
        f"('{h}', '{r}', {u}, {s}, {t})" for h, r, u, s, t in rows
    )
    inst.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        f"VALUES {values}"
    )


def test_create_insert_select_star(inst):
    setup_cpu(inst)
    res = inst.sql("SELECT * FROM cpu ORDER BY ts, host")
    assert res.names == ["host", "region", "usage_user", "usage_system", "ts"]
    assert res.num_rows == 5
    rows = res.rows()
    assert rows[0][0] == "h1" and rows[0][4] == 1000


def test_projection_and_arithmetic(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, usage_user + usage_system AS total FROM cpu "
        "WHERE ts = 1000 ORDER BY host"
    )
    assert res.names == ["host", "total"]
    assert res.rows() == [["h1", 11.0], ["h2", 33.0]]


def test_where_tag_pruning(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, usage_user FROM cpu WHERE region = 'us-east' "
        "ORDER BY usage_user"
    )
    assert res.rows() == [["h2", 40.0], ["h3", 50.0]]


def test_where_time_range(inst):
    setup_cpu(inst)
    res = inst.sql("SELECT count(*) FROM cpu WHERE ts >= 2000")
    assert res.rows() == [[3]]


def test_global_aggregate(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT count(*), sum(usage_user), min(usage_user), max(usage_user), "
        "avg(usage_user) FROM cpu"
    )
    assert res.rows() == [[5, 150.0, 10.0, 50.0, 30.0]]


def test_group_by_tag(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT region, avg(usage_user) AS a FROM cpu GROUP BY region "
        "ORDER BY region"
    )
    assert res.rows() == [["us-east", 45.0], ["us-west", 20.0]]


def test_group_by_two_tags_and_having(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, region, max(usage_user) AS m FROM cpu "
        "GROUP BY host, region HAVING m > 15 ORDER BY m DESC"
    )
    assert res.rows() == [
        ["h3", "us-east", 50.0], ["h2", "us-east", 40.0],
        ["h2", "us-west", 30.0], ["h1", "us-west", 20.0],
    ]


def test_group_by_time_bucket(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT date_trunc('second', ts) AS sec, count(*) AS c FROM cpu "
        "GROUP BY sec ORDER BY sec"
    )
    assert res.rows() == [[1000, 2], [2000, 2], [3000, 1]]


def test_post_aggregate_arithmetic(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT region, max(usage_user) - min(usage_user) AS spread "
        "FROM cpu GROUP BY region ORDER BY region"
    )
    assert res.rows() == [["us-east", 10.0], ["us-west", 20.0]]


def test_order_limit_offset(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, usage_user FROM cpu ORDER BY usage_user DESC "
        "LIMIT 2 OFFSET 1"
    )
    assert res.rows() == [["h2", 40.0], ["h2", 30.0]]


def test_distinct(inst):
    setup_cpu(inst)
    res = inst.sql("SELECT DISTINCT region FROM cpu ORDER BY region")
    assert res.rows() == [["us-east"], ["us-west"]]


def test_count_distinct(inst):
    setup_cpu(inst)
    res = inst.sql("SELECT count(DISTINCT host) FROM cpu")
    assert res.rows() == [[3]]


def test_last_value(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, last_value(usage_user) AS l FROM cpu "
        "GROUP BY host ORDER BY host"
    )
    assert res.rows() == [["h1", 20.0], ["h2", 40.0], ["h3", 50.0]]


def test_case_and_functions(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT host, CASE WHEN usage_user >= 30 THEN 'hot' ELSE 'cold' END "
        "AS temp FROM cpu WHERE ts = 1000 ORDER BY host"
    )
    assert res.rows() == [["h1", "cold"], ["h2", "hot"]]


def test_update_semantics_last_write_wins(inst):
    setup_cpu(inst)
    inst.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        "VALUES ('h1', 'us-west', 99.0, 9.0, 1000)"
    )
    res = inst.sql(
        "SELECT usage_user FROM cpu WHERE host = 'h1' AND ts = 1000"
    )
    assert res.rows() == [[99.0]]


def test_delete(inst):
    setup_cpu(inst)
    inst.sql("DELETE FROM cpu WHERE host = 'h1'")
    res = inst.sql("SELECT count(*) FROM cpu")
    assert res.rows() == [[3]]


def test_flush_and_restart_recovers(tmp_path):
    inst = Standalone(str(tmp_path / "data"))
    setup_cpu(inst)
    for t in inst.catalog.all_tables():
        t.flush()
    inst.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, ts) "
        "VALUES ('h4', 'eu', 60.0, 6.0, 4000)"
    )  # stays in WAL/memtable
    inst.close()

    inst2 = Standalone(str(tmp_path / "data"))
    res = inst2.sql("SELECT count(*), max(usage_user) FROM cpu")
    assert res.rows() == [[6, 60.0]]
    inst2.close()


def test_range_query_basic(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT ts, host, max(usage_user) RANGE '1s' FROM cpu "
        "ALIGN '1s' BY (host) ORDER BY ts, host"
    )
    rows = res.rows()
    # windows [t, t+1s): h1 has samples at 1000, 2000
    assert [r for r in rows if r[1] == "h1"] == [
        [1000, "h1", 10.0], [2000, "h1", 20.0],
    ]


def test_range_query_wider_window(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT ts, host, sum(usage_user) RANGE '2s' FROM cpu "
        "ALIGN '1s' BY (host) ORDER BY ts, host"
    )
    rows = [r for r in res.rows() if r[1] == "h1"]
    # h1 samples: 1000->10, 2000->20. Window [0,2000) = 10;
    # [1000,3000) = 30; [2000,4000) = 20
    assert rows == [[0, "h1", 10.0], [1000, "h1", 30.0], [2000, "h1", 20.0]]


def test_range_fill_prev(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT ts, host, max(usage_user) RANGE '1s' FILL PREV FROM cpu "
        "ALIGN '1s' BY (host) ORDER BY ts, host"
    )
    rows = [r for r in res.rows() if r[1] == "h1"]
    # h1: 1000, 2000 present, 3000 filled from 2000
    assert rows == [[1000, "h1", 10.0], [2000, "h1", 20.0],
                    [3000, "h1", 20.0]]


def test_show_and_describe(inst):
    setup_cpu(inst)
    res = inst.sql("SHOW TABLES")
    assert res.rows() == [["cpu"]]
    res = inst.sql("DESCRIBE TABLE cpu")
    cols = [r[0] for r in res.rows()]
    assert cols == ["host", "region", "usage_user", "usage_system", "ts"]
    sem = {r[0]: r[5] for r in res.rows()}
    assert sem["host"] == "TAG" and sem["ts"] == "TIMESTAMP"
    assert sem["usage_user"] == "FIELD"


def test_show_create_table(inst):
    setup_cpu(inst)
    res = inst.sql("SHOW CREATE TABLE cpu")
    ddl = res.rows()[0][1]
    assert "TIME INDEX" in ddl and "PRIMARY KEY" in ddl


def test_information_schema(inst):
    setup_cpu(inst)
    res = inst.sql(
        "SELECT table_name, engine FROM information_schema.tables "
        "WHERE table_schema = 'public'"
    )
    assert res.rows() == [["cpu", "mito"]]
    res = inst.sql(
        "SELECT column_name, semantic_type FROM information_schema.columns "
        "WHERE table_name = 'cpu' AND semantic_type = 'TAG' "
        "ORDER BY column_name"
    )
    assert res.rows() == [["host", "TAG"], ["region", "TAG"]]


def test_information_schema_breadth(inst):
    """The wider information_schema surface (VERDICT row 27): every
    provider answers, and the structured ones carry real catalog data."""
    setup_cpu(inst)
    inst.sql("CREATE VIEW v1 AS SELECT host, usage_user FROM cpu")

    r = inst.sql("SELECT table_name, view_definition FROM "
                 "information_schema.views")
    assert r.rows()[0][0] == "v1" and "usage_user" in r.rows()[0][1]

    r = inst.sql(
        "SELECT constraint_name, column_name FROM "
        "information_schema.key_column_usage WHERE table_name = 'cpu' "
        "ORDER BY ordinal_position"
    )
    names = {tuple(row) for row in r.rows()}
    assert ("PRIMARY", "host") in names and ("PRIMARY", "region") in names
    assert any(c == "TIME INDEX" for c, _ in names)

    r = inst.sql("SELECT constraint_type FROM "
                 "information_schema.table_constraints "
                 "WHERE table_name = 'cpu' ORDER BY constraint_type")
    assert [row[0] for row in r.rows()] == ["PRIMARY KEY", "TIME INDEX"]

    r = inst.sql("SELECT table_name, partition_name FROM "
                 "information_schema.partitions "
                 "WHERE table_name = 'cpu'")
    assert r.rows()[0][1] == "p0"

    r = inst.sql("SELECT region_id, is_leader, status FROM "
                 "information_schema.region_peers")
    assert r.num_rows >= 1 and r.rows()[0][1:] == ["Yes", "ALIVE"]

    r = inst.sql("SELECT metric_name, value FROM "
                 "information_schema.runtime_metrics "
                 "WHERE metric_name LIKE 'greptime%' OR 1 = 1 LIMIT 5")
    assert r.num_rows >= 1

    r = inst.sql("SELECT peer_type, version FROM "
                 "information_schema.cluster_info")
    assert r.rows()[0][0] == "STANDALONE"

    r = inst.sql("SELECT engine, support FROM information_schema.engines "
                 "ORDER BY engine")
    assert ["file", "metric", "tsdb"] == [row[0] for row in r.rows()]

    for tbl in ("procedure_info", "build_info", "character_sets",
                "collations"):
        r = inst.sql(f"SELECT * FROM information_schema.{tbl}")
        assert r.names, tbl


def test_alter_add_drop_column(inst):
    setup_cpu(inst)
    inst.sql("ALTER TABLE cpu ADD COLUMN usage_idle DOUBLE")
    inst.sql(
        "INSERT INTO cpu (host, region, usage_user, usage_system, usage_idle,"
        " ts) VALUES ('h1', 'us-west', 1.0, 1.0, 98.0, 5000)"
    )
    res = inst.sql(
        "SELECT usage_idle FROM cpu WHERE ts = 5000"
    )
    assert res.rows() == [[98.0]]
    # old rows read as NULL
    res = inst.sql("SELECT count(usage_idle) FROM cpu")
    assert res.rows() == [[1]]
    inst.sql("ALTER TABLE cpu DROP COLUMN usage_idle")
    res = inst.sql("SELECT * FROM cpu WHERE ts = 5000")
    assert "usage_idle" not in res.names


def test_multi_region_table(inst):
    inst.sql(
        "CREATE TABLE dist (host STRING, val DOUBLE, ts TIMESTAMP TIME INDEX,"
        " PRIMARY KEY (host)) WITH (num_regions = '4')"
    )
    values = ", ".join(
        f"('h{i % 16}', {float(i)}, {1000 + i})" for i in range(100)
    )
    inst.sql(f"INSERT INTO dist (host, val, ts) VALUES {values}")
    table = inst.catalog.table("public", "dist")
    assert len(table.regions) == 4
    assert sum(r.memtable.rows for r in table.regions) == 100
    res = inst.sql("SELECT count(*), sum(val) FROM dist")
    assert res.rows() == [[100, float(sum(range(100)))]]
    res = inst.sql(
        "SELECT host, count(*) AS c FROM dist GROUP BY host ORDER BY host"
    )
    assert res.num_rows == 16


def test_string_field_column(inst):
    inst.sql(
        "CREATE TABLE logs (app STRING, message STRING, level STRING, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (app))"
    )
    inst.sql(
        "INSERT INTO logs (app, message, level, ts) VALUES "
        "('web', 'boot ok', 'info', 1000), "
        "('web', 'disk full', 'error', 2000), "
        "('db', 'conn lost', 'error', 3000)"
    )
    res = inst.sql(
        "SELECT app, message FROM logs WHERE level = 'error' ORDER BY ts"
    )
    assert res.rows() == [["web", "disk full"], ["db", "conn lost"]]
    res = inst.sql(
        "SELECT level, count(*) AS c FROM logs GROUP BY level ORDER BY level"
    )
    assert res.rows() == [["error", 2], ["info", 1]]


def test_tableless_select(inst):
    res = inst.sql("SELECT 1 + 1, 'x'")
    assert res.rows() == [[2, "x"]]


def test_explain(inst):
    setup_cpu(inst)
    res = inst.sql("EXPLAIN SELECT region, max(usage_user) FROM cpu "
                   "WHERE host = 'h1' AND ts > 0 GROUP BY region")
    text = "\n".join(r[0] for r in res.rows())
    assert "Aggregate" in text and "matchers" in text


def test_use_database(inst):
    ctx = QueryContext()
    inst.execute_sql("CREATE DATABASE metrics", ctx)
    inst.execute_sql("USE metrics", ctx)
    assert ctx.database == "metrics"
    inst.execute_sql(
        "CREATE TABLE m1 (v DOUBLE, ts TIMESTAMP TIME INDEX)", ctx
    )
    assert inst.catalog.table_names("metrics") == ["m1"]


def test_device_aggregation_matches_host(inst):
    # same query through host and device paths must agree
    setup_cpu(inst)
    import copy

    host_engine = inst.query_engine
    res_host = inst.sql(
        "SELECT region, sum(usage_user), count(*) FROM cpu GROUP BY region "
        "ORDER BY region"
    )
    from greptimedb_tpu.query.executor import QueryEngine

    inst.query_engine = QueryEngine(prefer_device=True)
    res_dev = inst.sql(
        "SELECT region, sum(usage_user), count(*) FROM cpu GROUP BY region "
        "ORDER BY region"
    )
    inst.query_engine = host_engine
    assert res_host.rows() == res_dev.rows()
