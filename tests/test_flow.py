"""Flow (continuous aggregation) tests — the sqlness flow-case role of
/root/reference/tests/cases/standalone/common/flow/."""

import time

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    s.enable_flows()
    s.flows.tick_interval_s = 3600  # manual flushes in tests
    yield s
    s.close()


def _setup_source(inst):
    inst.sql(
        "CREATE TABLE requests (host STRING, status STRING, latency DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host, status))"
    )


def test_create_flow_and_aggregate(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW req_stats SINK TO req_summary AS "
        "SELECT date_bin('1 minute', ts) AS time_window, host, "
        "count(*) AS total, avg(latency) AS avg_latency "
        "FROM requests GROUP BY time_window, host"
    )
    assert inst.flows.flow_names() == ["req_stats"]

    inst.sql(
        "INSERT INTO requests VALUES "
        "('h1', '200', 10.0, 1700000000000), "
        "('h1', '200', 20.0, 1700000010000), "
        "('h2', '500', 30.0, 1700000020000), "
        "('h1', '200', 40.0, 1700000070000)"
    )
    inst.flows.flush_all()
    res = inst.sql(
        "SELECT time_window, host, total, avg_latency FROM req_summary "
        "ORDER BY time_window, host"
    )
    assert res.rows() == [
        [1699999980000, "h1", 2, 15.0],
        [1699999980000, "h2", 1, 30.0],
        [1700000040000, "h1", 1, 40.0],
    ]


def test_flow_incremental_updates(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW agg SINK TO sums AS "
        "SELECT date_bin('1 minute', ts) AS w, host, sum(latency) AS s "
        "FROM requests GROUP BY w, host"
    )
    inst.sql("INSERT INTO requests VALUES ('h1', '200', 5.0, 1700000000000)")
    inst.flows.flush_all()
    res = inst.sql("SELECT s FROM sums WHERE host = 'h1'")
    assert res.rows() == [[5.0]]
    # incremental: second insert into the SAME window updates the row
    inst.sql("INSERT INTO requests VALUES ('h1', '200', 7.0, 1700000030000)")
    inst.flows.flush_all()
    res = inst.sql("SELECT s FROM sums WHERE host = 'h1'")
    assert res.rows() == [[12.0]]


def test_flow_with_where_filter(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW errors SINK TO error_counts AS "
        "SELECT date_bin('1 minute', ts) AS w, host, count(*) AS errors "
        "FROM requests WHERE status = '500' GROUP BY w, host"
    )
    inst.sql(
        "INSERT INTO requests VALUES "
        "('h1', '200', 1.0, 1700000000000), "
        "('h1', '500', 2.0, 1700000010000), "
        "('h1', '500', 3.0, 1700000020000)"
    )
    inst.flows.flush_all()
    res = inst.sql("SELECT host, errors FROM error_counts")
    assert res.rows() == [["h1", 2]]


def test_flow_min_max(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW mm SINK TO minmax AS "
        "SELECT host, min(latency) AS lo, max(latency) AS hi "
        "FROM requests GROUP BY host"
    )
    inst.sql(
        "INSERT INTO requests VALUES ('h1', '200', 3.0, 1700000000000), "
        "('h1', '200', 9.0, 1700000010000)"
    )
    inst.flows.flush_all()
    res = inst.sql("SELECT host, lo, hi FROM minmax")
    assert res.rows() == [["h1", 3.0, 9.0]]


def test_flow_show_and_drop(inst):
    _setup_source(inst)
    inst.sql("CREATE FLOW f1 SINK TO s1 AS "
             "SELECT host, count(*) AS c FROM requests GROUP BY host")
    res = inst.sql("SHOW FLOWS")
    assert res.rows() == [["f1"]]
    res = inst.sql(
        "SELECT flow_name, source_table, sink_table "
        "FROM information_schema.flows"
    )
    assert res.rows() == [["f1", "requests", "s1"]]
    inst.sql("DROP FLOW f1")
    assert inst.flows.flow_names() == []


def test_flow_survives_restart(tmp_path):
    inst = Standalone(str(tmp_path / "data"))
    inst.enable_flows()
    inst.flows.tick_interval_s = 3600
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW agg SINK TO sums AS "
        "SELECT date_bin('1 minute', ts) AS w, host, sum(latency) AS s "
        "FROM requests GROUP BY w, host"
    )
    inst.sql("INSERT INTO requests VALUES ('h1', '200', 5.0, 1700000000000)")
    inst.flows.flush_all()
    inst.close()

    inst2 = Standalone(str(tmp_path / "data"))
    inst2.enable_flows()
    inst2.flows.tick_interval_s = 3600
    assert inst2.flows.flow_names() == ["agg"]
    # new inserts keep flowing into the sink after restart
    inst2.sql("INSERT INTO requests VALUES ('h2', '200', 8.0, 1700000005000)")
    inst2.flows.flush_all()
    res = inst2.sql("SELECT host, s FROM sums ORDER BY host")
    rows = res.rows()
    assert ["h2", 8.0] in rows
    inst2.close()


def test_flow_through_influx_ingest(inst):
    from greptimedb_tpu.servers.influx import write_lines

    _setup_source(inst)
    inst.sql(
        "CREATE FLOW agg SINK TO sums AS "
        "SELECT host, sum(latency) AS s FROM requests GROUP BY host"
    )
    write_lines(
        inst,
        "requests,host=h9,status=200 latency=4.5 1700000000000\n"
        "requests,host=h9,status=200 latency=5.5 1700000001000\n",
        precision="ms",
    )
    inst.flows.flush_all()
    res = inst.sql("SELECT host, s FROM sums")
    assert res.rows() == [["h9", 10.0]]


def test_flow_tagless_global_aggregate(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW tot SINK TO totals AS "
        "SELECT count(*) AS n, sum(latency) AS s FROM requests "
        "GROUP BY status"
    )
    inst.sql(
        "INSERT INTO requests VALUES ('h1', '200', 1.0, 1700000000000), "
        "('h2', '200', 2.0, 1700000000000)"
    )
    inst.flows.flush_all()
    res = inst.sql("SELECT n, s FROM totals")
    assert res.rows() == [[2, 3.0]]


def test_flow_non_windowed_upserts_not_appends(inst):
    _setup_source(inst)
    inst.sql(
        "CREATE FLOW agg SINK TO sums AS "
        "SELECT host, sum(latency) AS s FROM requests GROUP BY host"
    )
    inst.sql("INSERT INTO requests VALUES ('h1', '200', 5.0, 1700000000000)")
    inst.flows.flush_all()
    inst.sql("INSERT INTO requests VALUES ('h1', '200', 7.0, 1700000030000)")
    inst.flows.flush_all()
    res = inst.sql("SELECT host, s FROM sums")
    # one row per group — each flush overwrites (upsert), never appends
    assert res.rows() == [["h1", 12.0]]


def test_backfill_recovery_tick_does_not_deadlock(tmp_path):
    """flush_all's restart-recovery backfill must not self-deadlock on
    the non-reentrant flow lock (code-review r5 repro), and must
    re-derive state from the source."""
    import threading

    import numpy as np

    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.enable_flows(tick_interval_s=3600)
        inst.execute_sql(
            "create table s (host string primary key, v double, "
            "ts timestamp time index)"
        )
        inst.execute_sql(
            "create flow f sink to sums as select "
            "date_bin('1 minute', ts) as w, host, count(*) as n, "
            "sum(v) as t from s group by w, host"
        )
        inst.execute_sql("insert into s values ('a', 1.0, 1000), "
                         "('a', 2.0, 2000)")
        flow = inst.flows.maybe_flow("f")
        # simulate a restart that could not backfill at load time
        flow.state = {}
        flow.device_state = None
        flow.needs_backfill = True
        done = threading.Event()

        def run():
            inst.flows.flush_all()
            done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert done.wait(30), "flush_all deadlocked in backfill recovery"
        assert not flow.needs_backfill
        rows = inst.sql(
            "select host, n, t from sums order by host"
        ).rows()
        assert rows == [["a", 2, 3.0]]
    finally:
        inst.close()
