"""Partition-rule region pruning + per-SST sid-index row-group pruning
(VERDICT r2 task #8), both visible in EXPLAIN ANALYZE."""

import numpy as np
import pytest

from greptimedb_tpu.catalog.partition import PartitionRule
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.sql.parser import Parser


def _rule(columns, texts):
    return PartitionRule(columns, [Parser(t).expr() for t in texts], texts)


def test_partition_rule_routing_and_pruning():
    rule = _rule(["host"], [
        "host < 'h'", "host >= 'h' AND host < 'p'", "host >= 'p'",
    ])
    assert rule.region_of({"host": "alpha"}) == 0
    assert rule.region_of({"host": "h"}) == 1
    assert rule.region_of({"host": "zulu"}) == 2
    dest = rule.route_rows(
        {"host": np.asarray(["a", "m", "q", "m"], object)}, 4
    )
    assert dest.tolist() == [0, 1, 2, 1]
    assert rule.prune([("host", "eq", "alpha")]) == [0]
    assert rule.prune([("host", "in", ["alpha", "zulu"])]) == [0, 2]
    # non-eq ops can't pin the column: scan everything
    assert rule.prune([("host", "ne", "alpha")]) is None
    assert rule.prune([]) is None
    # contradictory constraints: nothing to scan
    assert rule.prune(
        [("host", "eq", "a"), ("host", "eq", "b")]
    ) == []


def test_partition_rule_json_roundtrip():
    rule = _rule(["host"], ["host < 'h'", "host >= 'h'"])
    again = PartitionRule.from_json(rule.to_json())
    assert again.region_of({"host": "a"}) == 0
    assert again.region_of({"host": "x"}) == 1


@pytest.fixture()
def part_inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"))
    inst.sql(
        "CREATE TABLE pt (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host)) "
        "PARTITION ON COLUMNS (host) (host < 'h', host >= 'h')"
    )
    table = inst.catalog.table("public", "pt")
    table.write(
        {"host": np.asarray(["a", "b", "x", "y"], object)},
        np.asarray([1000, 2000, 1000, 2000], np.int64),
        {"v": np.asarray([1.0, 2.0, 10.0, 20.0])},
    )
    yield inst, table
    inst.close()


def test_partitioned_table_routes_and_prunes(part_inst):
    inst, table = part_inst
    assert len(table.regions) == 2
    # rows landed in the right regions
    assert table.regions[0].series.num_series == 2  # a, b
    assert table.regions[1].series.num_series == 2  # x, y
    # queries see everything
    r = inst.sql("SELECT host, v FROM pt ORDER BY host")
    assert [list(x) for x in r.rows()] == [
        ["a", 1.0], ["b", 2.0], ["x", 10.0], ["y", 20.0],
    ]
    # a pinned partition column prunes regions, visible in EXPLAIN ANALYZE
    r = inst.sql("EXPLAIN ANALYZE SELECT v FROM pt WHERE host = 'a'")
    text = "\n".join(row[0] for row in r.rows())
    assert "regions_pruned: 1" in text
    assert "regions_scanned: 1" in text
    # restart keeps the rule (persisted in table options)
    assert table.partition_rule is not None


def test_partition_survives_restart(tmp_path):
    home = str(tmp_path / "data")
    inst = Standalone(home)
    inst.sql(
        "CREATE TABLE pr (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host)) "
        "PARTITION ON COLUMNS (host) (host < 'm', host >= 'm')"
    )
    inst.sql("INSERT INTO pr (host, v, ts) VALUES ('a', 1, 1000), ('z', 2, 1000)")
    inst.close()
    inst2 = Standalone(home)
    table = inst2.catalog.table("public", "pr")
    assert table.partition_rule is not None
    assert table.partition_rule.prune([("host", "eq", "a")]) == [0]
    r = inst2.sql("SELECT count(*) FROM pr")
    assert r.cols[0].values[0] == 2
    inst2.close()


def test_sst_sid_index_prunes_row_groups(tmp_path):
    """High-cardinality filtered query decodes only the row groups whose
    sid sets intersect the matched series."""
    inst = Standalone(str(tmp_path / "data"))
    inst.sql(
        "CREATE TABLE si (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "si")
    n_hosts, n_samples = 64, 32
    hosts = np.asarray([f"h{i:03d}" for i in range(n_hosts)], object)
    table.write(
        {"host": np.repeat(hosts, n_samples)},
        np.tile(np.arange(n_samples, dtype=np.int64) * 1000, n_hosts),
        {"v": np.arange(n_hosts * n_samples, dtype=np.float64)},
    )
    # flush with small row groups so pruning has something to skip
    region = table.regions[0]
    from greptimedb_tpu.storage import sst as S

    rows = region.memtable.scan()
    meta = S.write_sst(region.store, f"{region.prefix}/sst/test.parquet",
                       "test", rows, row_group_rows=128)
    assert meta.rows == n_hosts * n_samples
    # sid filter hits a single 32-row series: only 1 of 16 groups read
    got = S.read_sst(region.store, meta,
                     sids=np.asarray([5], np.int32))
    assert got is not None and len(got) == n_samples
    from greptimedb_tpu.query import stats

    with stats.collect() as st:
        S.read_sst(region.store, meta, sids=np.asarray([5], np.int32))
    assert st.counters["row_groups_total"] == 16
    assert st.counters["row_groups_read"] == 1
    inst.close()
