"""Fleet observability plane (ISSUE 15): heartbeat-carried node
telemetry, cluster-wide information_schema tables, federated metrics
and deep health — unit level plus an in-process wire topology (real
metasrv HTTP + datanode Flight servers + DistInstance frontend with
REAL heartbeat loops)."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.meta.kv import MemoryKv
from greptimedb_tpu.meta.metasrv import Metasrv
from greptimedb_tpu.storage.engine import EngineConfig


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(
        engine_config=EngineConfig(data_root=str(tmp_path / "data"),
                                   enable_background=False),
        prefer_device=False, warm_start=False,
    )
    inst.node_addr = "127.0.0.1:14000"
    yield inst
    inst.close()


def _setup_cpu(inst):
    inst.sql("create table cpu (ts timestamp time index, "
             "host string primary key, v double)")
    inst.sql("insert into cpu values (1000, 'h1', 1.0), "
             "(2000, 'h2', 2.0)")


# ---------------------------------------------------------------------
# node-stats payload + deep health (telemetry/node_stats.py)
# ---------------------------------------------------------------------

def test_node_stats_payload(inst):
    from greptimedb_tpu.telemetry import node_stats as ns

    _setup_cpu(inst)
    doc = ns.build_node_stats(inst)
    assert doc["role"] == "standalone"
    assert doc["addr"] == "127.0.0.1:14000"
    assert doc["version"]
    assert doc["uptime_s"] >= 0.0
    assert doc["regions"] >= 1
    assert doc["wal_backlog_rows"] >= 2   # unflushed inserts
    assert doc["memtable_bytes"] > 0
    # memory accountant tiers are present (values may be 0 cold)
    for k in ("mem_host_bytes", "mem_device_bytes",
              "compaction_backlog", "ingest_rows_total",
              "queries_total"):
        assert k in doc
    json.dumps(doc)  # the payload must survive the heartbeat wire


def test_deep_health_ok_and_degraded(inst, monkeypatch):
    from greptimedb_tpu.telemetry import node_stats as ns

    doc = ns.deep_health(inst)
    assert doc["status"] == "ok"
    assert doc["checks"]["engine"]["ok"]
    assert doc["checks"]["wal_appendable"]["ok"]
    assert doc["checks"]["device"]["ok"]
    assert all("ms" in c for c in doc["checks"].values())
    # one failing subsystem degrades the verdict without erroring the
    # probe (and without hiding the other checks)
    monkeypatch.setattr(
        inst.engine, "regions",
        lambda: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    doc = ns.deep_health(inst)
    assert doc["status"] == "degraded"
    assert not doc["checks"]["engine"]["ok"]
    assert "boom" in doc["checks"]["engine"]["detail"]
    assert doc["checks"]["device"]["ok"]   # others still ran


# ---------------------------------------------------------------------
# metasrv heartbeat enrichment + phi statuses (meta/metasrv.py)
# ---------------------------------------------------------------------

def test_metasrv_heartbeat_enrichment_ring_and_roles():
    ms = Metasrv(MemoryKv(), stats_history=4)
    ms.register_node(1, "127.0.0.1:5001")
    payload = {"role": "datanode", "addr": "127.0.0.1:5001",
               "uptime_s": 1.0, "regions": 3}
    for i in range(10):
        ms.heartbeat(1, {}, now_ms=1000.0 * i,
                     node_stats={**payload, "uptime_s": float(i)})
    node = ms.nodes[1]
    assert node.stats["uptime_s"] == 9.0
    # bounded ring: only the last 4 samples retained
    assert len(node.stats_history) == 4
    assert [s["uptime_s"] for s in node.stats_history] == [6.0, 7.0,
                                                           8.0, 9.0]
    # a frontend heartbeating a leader that never saw it registers
    # with ITS role — and the selector must never place regions on it
    ms.heartbeat(-5, {}, now_ms=9000.0, node_stats={
        "role": "frontend", "addr": "127.0.0.1:6001"})
    assert ms.nodes[-5].role == "frontend"
    assert ms.nodes[-5].addr == "127.0.0.1:6001"
    chosen = ms.selector.select(list(ms.nodes.values()), 4)
    assert set(chosen) == {1}
    # non-datanode heartbeats get no lease grant
    out = ms.heartbeat(-5, {}, now_ms=9500.0,
                       node_stats={"role": "frontend"})
    assert not any(i.get("type") == "grant_lease" for i in out)
    # the role rides EVERY beat, payload or not: with [fleet]
    # enrichment disabled (node_stats None) a frontend heartbeating a
    # restarted leader must still never become a placement target
    ms2 = Metasrv(MemoryKv())
    ms2.register_node(1, "127.0.0.1:5001")
    ms2.heartbeat(1, {}, now_ms=0.0)
    ms2.heartbeat(-7, {}, now_ms=0.0, role="frontend")
    assert ms2.nodes[-7].role == "frontend"
    assert set(ms2.selector.select(list(ms2.nodes.values()), 2)) == {1}
    # an existing registration heals too (mis-roled by a legacy beat)
    ms2.heartbeat(-7, {}, now_ms=500.0, role="flownode")
    assert ms2.nodes[-7].role == "flownode"
    # addr rides every beat as well: a restarted leader whose FIRST
    # contact with a datanode is a heartbeat (the client's beats never
    # failed across the transition, so it never re-registers) must
    # heal both the registry addr and the persisted peer book
    ms3 = Metasrv(MemoryKv())
    ms3.heartbeat(3, {}, now_ms=0.0, role="datanode",
                  addr="127.0.0.1:5003")
    assert ms3.nodes[3].addr == "127.0.0.1:5003"
    assert ms3.peers()[3] == "127.0.0.1:5003"
    ms3.heartbeat(3, {}, now_ms=500.0, role="datanode",
                  addr="127.0.0.1:5004")   # re-bound address
    assert ms3.peers()[3] == "127.0.0.1:5004"


def test_metasrv_phi_status_transitions():
    ms = Metasrv(MemoryKv(), acceptable_pause_ms=3000.0)
    ms.register_node(1, "127.0.0.1:5001")
    assert ms.node_status(1, now_ms=0.0) == "UNKNOWN"
    for i in range(5):
        ms.heartbeat(1, {}, now_ms=1000.0 * i)
    t0 = 4000.0
    seen = [ms.node_status(1, now_ms=t0 + dt)
            for dt in range(0, 40001, 250)]
    assert seen[0] == "ALIVE"
    assert seen[-1] == "DOWN"
    # the verdict passes through UNHEALTHY between ALIVE and DOWN and
    # is monotone (never recovers without a heartbeat)
    order = {"ALIVE": 0, "UNHEALTHY": 1, "DOWN": 2}
    ranks = [order[s] for s in seen]
    assert ranks == sorted(ranks)
    assert "UNHEALTHY" in seen
    # a fresh heartbeat restores ALIVE
    ms.heartbeat(1, {}, now_ms=t0 + 50000.0)
    assert ms.node_status(1, now_ms=t0 + 50000.0) == "ALIVE"
    # cluster_nodes carries the live verdict + phi + latest stats
    docs = ms.cluster_nodes(now_ms=t0 + 50000.0, history=True)
    assert docs[0]["status"] == "ALIVE"
    assert docs[0]["phi"] is not None
    assert isinstance(docs[0]["history"], list)


# ---------------------------------------------------------------------
# standalone cluster surfaces: nothing hardcoded
# ---------------------------------------------------------------------

def test_cluster_info_and_region_peers_standalone(inst):
    _setup_cpu(inst)
    r = inst.sql("select peer_type, peer_addr, status, uptime_s, "
                 "active_time from information_schema.cluster_info")
    assert r.num_rows == 1
    row = r.rows()[0]
    assert row[0] == "STANDALONE"
    assert row[1] == "127.0.0.1:14000"     # real addr, not ""
    assert row[2] == "ALIVE"
    assert row[3] > 0.0                    # real uptime
    assert int(row[4]) > 0                 # real activity timestamp
    r = inst.sql("select peer_addr, is_leader, status from "
                 "information_schema.region_peers")
    assert r.num_rows >= 1
    assert r.rows()[0] == ["127.0.0.1:14000", "Yes", "ALIVE"]
    # a downgraded (fenced) region reports its REAL state
    region = inst.catalog.table("public", "cpu").regions[0]
    region.writable = False
    try:
        r = inst.sql("select status from "
                     "information_schema.region_peers")
        assert r.rows()[0][0] == "DOWNGRADED"
    finally:
        region.writable = True


def test_cluster_tables_and_federated_surfaces_standalone(inst):
    from greptimedb_tpu.dist import fleet

    _setup_cpu(inst)
    r = inst.sql("select peer_id, role, addr, status, regions, "
                 "uptime_s from information_schema.cluster_node_stats")
    assert r.num_rows == 1
    row = r.rows()[0]
    assert row[1] == "standalone" and row[2] == "127.0.0.1:14000"
    assert row[3] == "ALIVE" and row[4] >= 1
    # the four fan-out tables answer locally with peer/peer_status
    for t in ("cluster_runtime_metrics", "cluster_memory_pools",
              "cluster_statement_statistics"):
        r = inst.sql(f"select distinct peer, peer_status from "
                     f"information_schema.{t}")
        assert r.rows() == [["127.0.0.1:14000", "ok"]], t
    # the device-program registry is PROCESS-wide: it may be empty (this
    # file alone) or carry earlier tests' programs (full suite) — either
    # way every row is local and ok, and the query never errors
    r = inst.sql("select distinct peer, peer_status from "
                 "information_schema.cluster_device_programs")
    assert r.rows() in ([], [["127.0.0.1:14000", "ok"]])
    r = inst.sql("select count(*) from "
                 "information_schema.cluster_runtime_metrics "
                 "where metric_name like 'gtpu_%'")
    assert r.rows()[0][0] > 0
    # federated metrics: node/role labels on our families, TTL cache
    text = fleet.federated_metrics(inst)
    assert 'node="127.0.0.1:14000"' in text
    assert 'role="standalone"' in text
    assert "gtpu_" in text
    assert fleet.federated_metrics(inst) is text   # cached within TTL
    assert fleet.federated_metrics(inst, force=True) is not text
    doc = fleet.federated_health(inst)
    assert doc["status"] == "ok"
    assert doc["nodes"][0]["checks"]["engine"]["ok"]


def test_http_cluster_and_deep_health_routes(inst):
    from greptimedb_tpu.servers.http import HttpServer

    _setup_cpu(inst)
    srv = HttpServer(inst, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/health?deep=1",
                                    timeout=30) as resp:
            doc = json.loads(resp.read())
        assert doc["status"] == "ok" and doc["checks"]
        with urllib.request.urlopen(f"{base}/v1/cluster/metrics",
                                    timeout=30) as resp:
            text = resp.read().decode()
        assert 'node="127.0.0.1:14000"' in text and "gtpu_" in text
        with urllib.request.urlopen(f"{base}/v1/cluster/health",
                                    timeout=30) as resp:
            doc = json.loads(resp.read())
        assert doc["status"] == "ok"
        # a degraded node answers 503 on the deep probe (plain /health
        # stays a liveness 200)
        real = inst.engine.regions
        inst.engine.regions = (
            lambda: (_ for _ in ()).throw(RuntimeError("down"))
        )
        try:
            from greptimedb_tpu.telemetry import node_stats as ns

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/health?deep=1",
                                       timeout=30)
            assert ei.value.code == 503
            with urllib.request.urlopen(f"{base}/health",
                                        timeout=30) as resp:
                assert resp.status == 200
        finally:
            inst.engine.regions = real
    finally:
        srv.stop()


# ---------------------------------------------------------------------
# export loop identity labels (satellite)
# ---------------------------------------------------------------------

def test_export_stamps_node_role_labels(inst):
    from greptimedb_tpu.telemetry.export import (
        ExportMetricsTask,
        scrape_registry,
    )
    from greptimedb_tpu.telemetry.metrics import global_registry

    global_registry.counter("test_fleet_export_total", "t").inc(3)
    series = scrape_registry(
        now_ms=5, extra_labels={"node": "n1", "role": "datanode"}
    )
    match = [lab for lab, _s in series
             if lab["__name__"] == "test_fleet_export_total"]
    assert match and match[0]["node"] == "n1"
    assert match[0]["role"] == "datanode"
    # a metric already carrying the label keeps its own value
    global_registry.counter(
        "test_fleet_export_labeled_total", "t", ("node",)
    ).labels("other").inc()
    series = scrape_registry(extra_labels={"node": "n1"})
    match = [lab for lab, _s in series
             if lab["__name__"] == "test_fleet_export_labeled_total"]
    assert match[0]["node"] == "other"
    # the task resolves identity from the instance at tick time and the
    # re-ingested series are tagged — two roles can never collide
    task = ExportMetricsTask(inst, db="t_fleet_export",
                             interval_s=3600.0)
    inst.catalog.create_database("t_fleet_export", if_not_exists=True)
    task.tick()
    r = inst.sql("select node, role from "
                 "t_fleet_export.test_fleet_export_total limit 1")
    assert r.rows()[0] == ["127.0.0.1:14000", "standalone"]


# ---------------------------------------------------------------------
# in-process wire topology: real heartbeats, fan-out, degradation
# ---------------------------------------------------------------------

def test_wire_fleet_fanout_and_down_degradation(tmp_path):
    pytest.importorskip("pyarrow.flight")
    from greptimedb_tpu.dist import fleet
    from greptimedb_tpu.dist.frontend import DistInstance
    from greptimedb_tpu.dist.region_server import RegionServer
    from greptimedb_tpu.servers.flight import FlightFrontend
    from greptimedb_tpu.servers.meta_http import MetasrvServer

    meta = MetasrvServer(
        addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta"),
        acceptable_pause_ms=1500.0,
    ).start()
    meta_addr = f"127.0.0.1:{meta.port}"
    dns, stops = [], []
    fe = None
    try:
        for i in range(2):
            dn = Standalone(
                engine_config=EngineConfig(
                    data_root=str(tmp_path / f"dn{i}"),
                    enable_background=False,
                ),
                prefer_device=False, warm_start=False,
            )
            dn.region_server = RegionServer(
                dn.engine, str(tmp_path / f"dn{i}")
            )
            fs = FlightFrontend(dn, port=0).start()
            addr = f"127.0.0.1:{fs.server.port}"
            stops.append(fleet.start_heartbeat(
                meta_addr, i, dn, role="datanode", addr=addr,
                interval_s=0.3,
            ))
            dns.append((dn, fs, addr))
        fe = DistInstance(str(tmp_path / "fe"), meta_addr,
                          prefer_device=False)
        fe.node_addr = "127.0.0.1:18000"
        stops.append(fleet.start_heartbeat(
            meta_addr,
            fleet.derive_node_id("frontend", fe.node_addr), fe,
            role="frontend", addr=fe.node_addr, interval_s=0.3,
        ))
        # wait for every heartbeat to land
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            r = fe.sql("select role, status from "
                       "information_schema.cluster_node_stats")
            rows = r.rows()
            if (sum(1 for ro, st in rows
                    if ro == "datanode" and st == "ALIVE") >= 2
                    and any(ro == "frontend" and st == "ALIVE"
                            for ro, st in rows)):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"fleet never converged: {rows}")

        fe.execute_sql(
            "create table cpu (ts timestamp time index, host string "
            "primary key, v double) with (num_regions = 2)"
        )
        fe.sql("insert into cpu values (1000, 'h1', 1.0), "
               "(2000, 'h2', 2.0)")
        # one row per live node with non-empty addr / uptime / memory
        r = fe.sql("select role, addr, uptime_s, mem_host_bytes, "
                   "mem_device_bytes, regions from "
                   "information_schema.cluster_node_stats "
                   "where role != 'metasrv'")
        assert r.num_rows == 3
        for role, addr, up, mh, md, regions in r.rows():
            assert addr, role
            assert up > 0.0, role
            assert mh >= 0 and md >= 0
        # datanode rows carry their region counts via heartbeats
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            r = fe.sql("select sum(regions) from information_schema."
                       "cluster_node_stats where role = 'datanode'")
            if int(r.rows()[0][0]) >= 2:
                break
            time.sleep(0.3)
        assert int(r.rows()[0][0]) >= 2
        # region_peers: real addrs + detector status
        r = fe.sql("select peer_addr, status from "
                   "information_schema.region_peers")
        assert r.num_rows == 2
        assert {a for a, _s in r.rows()} == {dns[0][2], dns[1][2]}
        assert all(s == "ALIVE" for _a, s in r.rows())
        # cluster fan-out tables: rows from every node
        r = fe.sql("select distinct peer, peer_status from "
                   "information_schema.cluster_runtime_metrics")
        assert {(p, s) for p, s in r.rows()} == {
            (fe.node_addr, "ok"), (dns[0][2], "ok"), (dns[1][2], "ok"),
        }
        r = fe.sql("select count(*) from information_schema."
                   "cluster_memory_pools where peer_status = 'ok'")
        assert int(r.rows()[0][0]) > 0
        # federated metrics: every node's families, node-labeled
        text = fleet.federated_metrics(fe, force=True)
        for addr in (fe.node_addr, dns[0][2], dns[1][2]):
            assert f'node="{addr}"' in text, addr
        assert "gtpu_fleet_heartbeats_total" in text
        doc = fleet.federated_health(fe)
        assert doc["status"] == "ok"
        assert len(doc["nodes"]) == 4   # fe + 2 dn + metasrv

        # SIGKILL-equivalent: stop heartbeats + tear the node down
        stops[1]()
        dns[1][1].close(grace_s=1.0)
        dns[1][0].close()
        deadline = time.monotonic() + 25
        status = None
        while time.monotonic() < deadline:
            r = fe.sql("select status from information_schema."
                       "cluster_node_stats where peer_id = 1")
            status = r.rows()[0][0] if r.num_rows else None
            if status == "DOWN":
                break
            time.sleep(0.3)
        assert status == "DOWN", status
        # fan-out degrades to reachable peers + status, fast and
        # inside the request deadline (the dead peer errors at
        # CONNECT, not after a timeout)
        t0 = time.monotonic()
        r = fe.sql("select distinct peer, peer_status from "
                   "information_schema.cluster_runtime_metrics")
        elapsed = time.monotonic() - t0
        rows = {p: s for p, s in r.rows()}
        assert rows[fe.node_addr] == "ok"
        assert rows[dns[0][2]] == "ok"
        assert rows[dns[1][2]] != "ok"          # degraded, marked
        assert elapsed < fleet.config()["fanout_timeout_s"] + 3.0
        # federated health reports the dead node as unreachable
        doc = fleet.federated_health(fe)
        assert doc["status"] == "degraded"
        dead = [n for n in doc["nodes"] if n["peer"] == dns[1][2]]
        assert dead and dead[0]["status"] == "unreachable"
    finally:
        for s in stops[:1] + stops[2:]:
            try:
                s()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if fe is not None:
            fe.close()
        dns[0][1].close(grace_s=1.0)
        dns[0][0].close()
        meta.close()
