"""pprof debug routes, TLS serving, and the metrics self-export task.

Reference surface: src/servers/src/http/pprof.rs + mem_prof.rs,
src/servers/src/tls.rs, src/servers/src/export_metrics.rs.
"""

import json
import ssl
import subprocess
import threading
import time
import urllib.request

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.http import HttpServer
from greptimedb_tpu.telemetry import pprof
from greptimedb_tpu.telemetry.export import ExportMetricsTask, scrape_registry
from greptimedb_tpu.telemetry.metrics import global_registry


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


@pytest.fixture()
def server(inst):
    srv = HttpServer(inst, port=0).start()
    yield srv
    srv.stop()


def _get(srv, path, scheme="http", ctx=None):
    url = f"{scheme}://127.0.0.1:{srv.port}{path}"
    with urllib.request.urlopen(url, timeout=30, context=ctx) as r:
        return r.status, r.read()


# ---------------------------------------------------------------------
# pprof
# ---------------------------------------------------------------------

def test_sample_cpu_captures_running_code():
    stop = threading.Event()

    def busy_loop_for_profiler():
        while not stop.wait(0.001):
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy_loop_for_profiler, name="busy")
    t.start()
    try:
        stacks = pprof.sample_cpu(0.4, hz=200)
    finally:
        stop.set()
        t.join()
    collapsed = pprof.render_collapsed(stacks)
    assert "busy_loop_for_profiler" in collapsed
    report = pprof.render_report(stacks)
    assert "samples:" in report and "self%" in report


def test_mem_profile_reports_sites():
    first = pprof.mem_profile()
    if "started" in first:
        # tracked from now on; allocate something visible
        _hold = [bytearray(256) for _ in range(2000)]
        out = pprof.mem_profile(10)
        assert "traced current=" in out
        del _hold


def test_debug_prof_routes(server):
    code, body = _get(server, "/debug/prof/cpu?seconds=0.2")
    assert code == 200 and b"samples:" in body
    code, body = _get(
        server, "/debug/prof/cpu?seconds=0.2&format=collapsed"
    )
    assert code == 200
    code, body = _get(server, "/debug/prof/mem")
    assert code == 200


def test_cpu_profile_speedscope_format(server):
    stop = threading.Event()

    def busy_speedscope_target():
        while not stop.wait(0.001):
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy_speedscope_target, name="busy-ss")
    t.start()
    try:
        code, body = _get(
            server, "/debug/prof/cpu?seconds=0.4&format=speedscope"
        )
    finally:
        stop.set()
        t.join()
    assert code == 200
    doc = json.loads(body)
    assert doc["$schema"] == (
        "https://www.speedscope.app/file-format-schema.json"
    )
    frames = doc["shared"]["frames"]
    prof = doc["profiles"][0]
    assert prof["type"] == "sampled"
    assert len(prof["samples"]) == len(prof["weights"])
    assert prof["samples"], "no samples captured"
    # every sample is a stack of valid frame indices
    for stack in prof["samples"]:
        assert stack and all(0 <= i < len(frames) for i in stack)
    assert prof["endValue"] == sum(prof["weights"])
    names = "".join(f["name"] for f in frames)
    assert "busy_speedscope_target" in names


def test_mem_profile_diff_reports_growth():
    pprof.mem_profile()          # ensures tracemalloc is tracing
    pprof.mem_profile()          # baseline snapshot stored
    hold = [bytearray(1024) for _ in range(3000)]
    out = pprof.mem_profile(top=40, diff=True)
    assert "since previous snapshot" in out
    # growth is signed and attributed to this allocation site
    assert "test_observability_ext.py" in out, out
    assert "+" in out
    del hold
    # the diff updated the stored snapshot: an immediate second diff
    # reports against NOW, not the original baseline
    out2 = pprof.mem_profile(top=5, diff=True)
    assert "since previous snapshot" in out2


def test_mem_profile_diff_http_route(server):
    _get(server, "/debug/prof/mem")        # start/advance snapshots
    code, body = _get(server, "/debug/prof/mem?diff=1&top=10")
    assert code == 200
    assert b"snapshot" in body


# ---------------------------------------------------------------------
# metrics self-export
# ---------------------------------------------------------------------

def test_scrape_registry_parses_labels():
    global_registry.counter(
        "test_export_requests", "t", ("route", "code")
    ).labels("/v1/sql", "200").inc(3)
    series = scrape_registry(now_ms=1234)
    match = [
        (lab, s) for lab, s in series
        if lab["__name__"] == "test_export_requests"
        and lab.get("route") == "/v1/sql"
    ]
    assert match
    labels, samples = match[0]
    assert labels["code"] == "200"
    assert samples == [(3.0, 1234)]


def test_export_metrics_task_self_import(inst):
    global_registry.counter("test_selfimport_total", "t").inc(7)
    task = ExportMetricsTask(inst, db="greptime_metrics",
                             interval_s=3600.0).start()
    try:
        task.tick()
        res = inst.sql(
            "select greptime_value from greptime_metrics.test_selfimport_total"
        )
        assert res.num_rows >= 1
        assert float(res.cols[0].values[0]) >= 7.0
    finally:
        task.stop()


# ---------------------------------------------------------------------
# TLS
# ---------------------------------------------------------------------

def test_https_serving(inst, tmp_path):
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    p = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        capture_output=True,
    )
    if p.returncode != 0:
        pytest.skip(f"openssl unavailable: {p.stderr[:120]}")
    srv = HttpServer(inst, port=0, tls_cert=str(cert),
                     tls_key=str(key)).start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        code, body = _get(srv, "/health", scheme="https", ctx=ctx)
        assert code == 200
        assert json.loads(body) == {}
    finally:
        srv.stop()


def test_scrape_registry_brace_in_label_value():
    """ADVICE r3 (low): a '}' inside a quoted label value must not
    truncate the label block."""
    from greptimedb_tpu.telemetry.export import _LABEL, _LINE

    line = 'greptime_http{path="a}b",method="GET"} 3'
    m = _LINE.match(line)
    assert m is not None and m.group("value") == "3"
    labels = dict(_LABEL.findall(m.group("labels")))
    assert labels == {"path": "a}b", "method": "GET"}
