"""Decimal128 type: schema, ingest, query, persistence, COPY.

Reference: src/common/decimal/src/decimal128.rs + the sqlness decimal
cases. Engine representation is float64 (exact round-trip for
precision <= 15); schema/wire/Parquet carry the exact (p, s) type.
"""

import numpy as np
import pyarrow.parquet as pq
import pytest

from greptimedb_tpu.datatypes.types import ConcreteDataType, TypeId
from greptimedb_tpu.instance import Standalone


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    inst.execute_sql(
        "create table prices (ts timestamp time index, "
        "item string primary key, price decimal(10, 2), qty bigint)"
    )
    inst.execute_sql(
        "insert into prices (ts, item, price, qty) values "
        "(1000, 'a', 12.25, 3), (2000, 'b', 0.10, 1), "
        "(3000, 'c', 1999.99, 2)"
    )
    yield inst
    inst.close()


def test_type_parsing_and_name():
    t = ConcreteDataType.from_name("decimal(10,2)")
    assert t.id == TypeId.DECIMAL and (t.precision, t.scale) == (10, 2)
    assert t.name == "decimal(10,2)"
    assert ConcreteDataType.from_name(t.name) == t  # persistence roundtrip
    assert ConcreteDataType.from_name("numeric").precision == 38
    with pytest.raises(ValueError):
        ConcreteDataType.decimal128(50, 2)
    with pytest.raises(ValueError):
        ConcreteDataType.decimal128(10, 12)


def test_select_renders_exact_scale(inst):
    r = inst.sql("select item, price from prices order by ts")
    rows = r.rows()
    assert rows[0][1] == "12.25"
    assert rows[1][1] == "0.10"
    assert rows[2][1] == "1999.99"


def test_describe_and_show_create(inst):
    r = inst.sql("show columns from prices")
    by_name = dict(zip(r.cols[0].values, r.cols[1].values))
    assert by_name["price"] == "decimal(10,2)"
    r = inst.sql("show create table prices")
    assert "DECIMAL(10,2)" in str(r.cols[1].values[0]).upper()


def test_filter_and_aggregate(inst):
    r = inst.sql("select item from prices where price > 10 order by ts")
    assert list(r.cols[0].values) == ["a", "c"]
    r = inst.sql("select sum(price) from prices")
    assert float(r.cols[0].values[0]) == pytest.approx(2012.34)


def test_persistence_roundtrip(tmp_path, inst):
    inst.catalog.table("public", "prices").flush()
    inst.close()
    inst2 = Standalone(str(tmp_path / "data"), prefer_device=False,
                       warm_start=False)
    try:
        cs = inst2.catalog.table("public", "prices").schema.column("price")
        assert cs.data_type == ConcreteDataType.decimal128(10, 2)
        r = inst2.sql("select price from prices order by ts")
        assert r.rows()[0][0] == "12.25"
    finally:
        inst2.close()


def test_copy_to_writes_decimal_parquet(tmp_path, inst):
    path = str(tmp_path / "prices.parquet")
    inst.execute_sql(f"COPY prices TO '{path}' WITH (format = 'parquet')")
    schema = pq.read_schema(path)
    f = schema.field("price")
    assert str(f.type) == "decimal128(10, 2)"
    # and back
    inst.execute_sql("create database rt")
    from greptimedb_tpu.session import QueryContext

    ctx = QueryContext(database="rt")
    inst.execute_sql(
        "create table prices (ts timestamp time index, "
        "item string primary key, price decimal(10, 2), qty bigint)", ctx
    )
    inst.execute_sql(
        f"COPY prices FROM '{path}' WITH (format = 'parquet')", ctx
    )
    r = inst.sql("select price from rt.prices order by ts")
    assert r.rows()[0][0] == "12.25"


def test_insert_string_literal_value(inst):
    inst.execute_sql(
        "insert into prices (ts, item, price, qty) values "
        "(4000, 'd', '7.77', 1)"
    )
    r = inst.sql("select price from prices where item = 'd'")
    assert r.rows()[0][0] == "7.77"
