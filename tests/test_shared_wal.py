"""Shared-topic WAL: many regions multiplexed into one log.

Reference: src/log-store/src/kafka/log_store.rs (shared Kafka topics) +
src/mito2/src/wal/entry_distributor.rs (per-region demultiplexing).
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.storage.wal import (
    RegionWal,
    SharedWalTopic,
    TopicRegionLog,
    _unframe_topic_entry,
)


def test_interleaved_appends_demultiplex(tmp_path):
    topic = SharedWalTopic(RegionWal(str(tmp_path / "t0")))
    a = TopicRegionLog(topic, 1)
    b = TopicRegionLog(topic, 2)
    assert a.append(b"a0") == 0
    assert b.append(b"b0") == 0      # per-region ids are independent
    assert a.append(b"a1") == 1
    assert a.append_batch([b"a2", b"a3"]) == 3
    assert b.append(b"b1") == 1
    assert [(e.entry_id, e.payload) for e in a.replay(0)] == [
        (0, b"a0"), (1, b"a1"), (2, b"a2"), (3, b"a3")
    ]
    assert [(e.entry_id, e.payload) for e in b.replay(1)] == [(1, b"b1")]
    assert a.next_entry_id == 4
    topic.close()


def test_recovery_rebuilds_per_region_ids(tmp_path):
    topic = SharedWalTopic(RegionWal(str(tmp_path / "t0")))
    TopicRegionLog(topic, 1).append_batch([b"x", b"y"])
    TopicRegionLog(topic, 7).append(b"z")
    topic.close()
    # fresh open scans the physical log and restores per-region state
    topic2 = SharedWalTopic(RegionWal(str(tmp_path / "t0")))
    a = TopicRegionLog(topic2, 1)
    assert a.next_entry_id == 2
    assert [e.payload for e in a.replay(0)] == [b"x", b"y"]
    assert a.append(b"w") == 2
    assert [e.payload for e in TopicRegionLog(topic2, 7).replay(0)] == [b"z"]
    topic2.close()


def test_truncation_honors_slowest_region(tmp_path):
    # tiny segments so obsolete() can actually drop files
    inner = RegionWal(str(tmp_path / "t0"), segment_bytes=64)
    topic = SharedWalTopic(inner)
    a = TopicRegionLog(topic, 1)
    b = TopicRegionLog(topic, 2)
    for i in range(10):
        a.append(b"a" * 16)
        b.append(b"b" * 16)
    # region 1 flushed everything; region 2 flushed nothing
    a.obsolete(9)
    assert [e.payload for e in b.replay(0)] == [b"b" * 16] * 10
    # now region 2 catches up; the physical log can shrink
    before = len(inner._segments())
    b.obsolete(9)
    after = len(inner._segments())
    assert after <= before
    assert a.replay(0) == [] and b.replay(0) == []
    topic.close()


def test_drop_region_unpins_truncation(tmp_path):
    inner = RegionWal(str(tmp_path / "t0"), segment_bytes=64)
    topic = SharedWalTopic(inner)
    a = TopicRegionLog(topic, 1)
    b = TopicRegionLog(topic, 2)
    for _ in range(8):
        a.append(b"a" * 16)
    b.append(b"live")
    # region 1 is dropped without ever flushing: its dead entries must
    # not pin the log forever
    a.drop()
    b.append(b"live2")
    b.obsolete(1)
    assert b.replay(0) == []
    # everything is obsolete -> the physical log shrank to (at most) the
    # active tail segment
    assert len(inner._segments()) <= 1
    topic.close()


def test_topic_assignment_survives_topic_count_change(tmp_path):
    from greptimedb_tpu.storage.engine import TsdbEngine
    from greptimedb_tpu.storage.region import RegionMetadata, RegionOptions

    def meta(rid):
        return RegionMetadata(
            region_id=rid, table="t", tag_names=["h"], field_names=["v"],
            ts_name="ts", options=RegionOptions(),
        )

    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False,
                       wal_backend="shared", wal_topics=4)
    eng = TsdbEngine(cfg)
    r3 = eng.create_region(meta(3))
    r3.write({"h": np.asarray(["x"], object)},
             np.asarray([1000], np.int64), {"v": np.asarray([1.0])})
    assert r3.wal.topic is eng._topics[3]  # 3 % 4
    eng.close()

    # operator shrinks wal.topics; region 3 must keep topic_3 (a fresh
    # modulus would replay the wrong topic and lose the unflushed row)
    cfg2 = EngineConfig(data_root=str(tmp_path / "d"),
                        enable_background=False,
                        wal_backend="shared", wal_topics=2)
    eng2 = TsdbEngine(cfg2)
    r3b = eng2.open_region(meta(3))
    assert r3b.wal.topic is eng2._topics[3]
    res = r3b.scan(field_names=["v"])
    assert res.rows is not None and list(res.rows.fields["v"]) == [1.0]
    eng2.close()


@pytest.fixture()
def shared_inst(tmp_path):
    inst = Standalone(
        engine_config=EngineConfig(
            data_root=str(tmp_path / "data"), enable_background=False,
            wal_backend="shared", wal_topics=2,
        ),
        prefer_device=False, warm_start=False,
    )
    yield inst
    inst.close()


def test_engine_shared_wal_replay_after_restart(tmp_path, shared_inst):
    inst = shared_inst
    for t in ("m1", "m2", "m3"):
        inst.execute_sql(
            f"create table {t} (ts timestamp time index, "
            f"host string primary key, v double)"
        )
        inst.catalog.table("public", t).write(
            {"host": np.asarray(["a", "b"], object)},
            np.asarray([1000, 2000], np.int64),
            {"v": np.asarray([1.0, 2.0])},
        )
    # regions from 3 tables share 2 topics
    import os

    wal_root = os.path.join(str(tmp_path / "data"), "wal")
    topics = [d for d in os.listdir(wal_root) if d.startswith("topic_")]
    region_dirs = [d for d in os.listdir(wal_root)
                   if d.startswith("region_") and os.listdir(
                       os.path.join(wal_root, d))]
    assert len(topics) >= 1 and not region_dirs
    inst.close()

    # crash-restart: rows come back from the shared log (memtable only,
    # nothing was flushed)
    inst2 = Standalone(
        engine_config=EngineConfig(
            data_root=str(tmp_path / "data"), enable_background=False,
            wal_backend="shared", wal_topics=2,
        ),
        prefer_device=False, warm_start=False,
    )
    try:
        for t in ("m1", "m2", "m3"):
            r = inst2.sql(f"select v from {t} order by ts")
            assert list(r.cols[0].values) == [1.0, 2.0]
    finally:
        inst2.close()


def test_truncated_region_ids_never_regress_below_flushed(tmp_path):
    """ADVICE r3 (high): once truncation has erased ALL of a region's
    physical entries, a restart must not hand out entry ids below the
    region's manifest flushed watermark — otherwise the appends land at
    reid 0..k < flushed and replay(flushed+1) after the NEXT crash skips
    them: silent data loss."""
    import os

    from greptimedb_tpu.storage.engine import TsdbEngine
    from greptimedb_tpu.storage.region import RegionMetadata, RegionOptions

    def meta(rid, tbl):
        return RegionMetadata(
            region_id=rid, table=tbl, tag_names=["h"], field_names=["v"],
            ts_name="ts", options=RegionOptions(),
        )

    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False,
                       wal_backend="shared", wal_topics=1)
    eng = TsdbEngine(cfg)
    # tiny segments so obsolete() can drop the prefix holding region A
    wal_root = os.path.join(cfg.data_root, "wal")
    os.makedirs(wal_root, exist_ok=True)
    eng._topics[0] = SharedWalTopic(
        RegionWal(os.path.join(wal_root, "topic_0"), segment_bytes=64)
    )
    ra = eng.create_region(meta(1, "a"))
    rb = eng.create_region(meta(2, "b"))
    for i in range(5):
        ra.write({"h": np.asarray(["x"], object)},
                 np.asarray([1000 + i], np.int64),
                 {"v": np.asarray([float(i)])})
    ra.flush()
    for i in range(5):
        rb.write({"h": np.asarray(["y"], object)},
                 np.asarray([1000 + i], np.int64),
                 {"v": np.asarray([float(i)])})
    rb.flush()
    # every physical entry of region A is gone from the shared log
    assert all(
        _unframe_topic_entry(e.payload)[0] != 1
        for e in eng._topics[0].inner.replay(0)
    )
    flushed_a = ra.manifest.state.flushed_entry_id
    assert flushed_a == 4
    del eng, ra, rb  # crash: no close, no flush

    eng2 = TsdbEngine(cfg)
    ra2 = eng2.open_region(meta(1, "a"))
    ra2.write({"h": np.asarray(["x"], object)},
              np.asarray([9000], np.int64), {"v": np.asarray([99.0])})
    # the new entry's id must sit ABOVE the flushed watermark
    assert ra2.wal.next_entry_id - 1 > flushed_a
    del eng2, ra2  # crash again before any flush

    eng3 = TsdbEngine(cfg)
    ra3 = eng3.open_region(meta(1, "a"))
    res = ra3.scan(field_names=["v"])
    got = sorted(res.rows.fields["v"])
    assert got == [0.0, 1.0, 2.0, 3.0, 4.0, 99.0]
    eng3.close()
