"""Tests for pipeline ETL, script engine, metric engine, COPY, auth, and
fulltext matching (the aux-subsystem tiers of SURVEY.md §2.3/2.5)."""

import json
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.pipeline import Pipeline, PipelineManager
from greptimedb_tpu.query.fulltext import eval_matches
from greptimedb_tpu.script import PyEngine


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


# ----------------------------------------------------------------------
# pipeline ETL
# ----------------------------------------------------------------------

ACCESS_LOG_PIPELINE = """
processors:
  - dissect:
      fields: [message]
      patterns:
        - '%{ip} - %{user} [%{ts}] "%{method} %{path}" %{status} %{size}'
  - date:
      fields: [ts]
      formats: ['%d/%b/%Y:%H:%M:%S']
  - letter:
      fields: [method]
      method: lower
transform:
  - fields: [ip, method, status]
    type: string
    index: tag
  - fields: [path, user]
    type: string
  - fields: [size]
    type: int64
  - fields: [ts]
    type: time
    index: timestamp
"""


def test_pipeline_processors():
    p = Pipeline(ACCESS_LOG_PIPELINE)
    rows = p.run([{
        "message": '1.2.3.4 - alice [15/Nov/2023:10:30:00] '
                   '"GET /api/users" 200 1234'
    }])
    assert len(rows) == 1
    r = rows[0]
    assert r["ip"] == "1.2.3.4"
    assert r["method"] == "get"
    assert r["status"] == "200"
    assert r["size"] == 1234
    assert r["ts"] == 1700044200000


def test_pipeline_ingest_creates_table(inst):
    mgr = PipelineManager.get(inst)
    mgr.upsert_pipeline("access", ACCESS_LOG_PIPELINE)
    n = mgr.ingest("public", "access_logs", "access", [
        {"message": '1.2.3.4 - alice [15/Nov/2023:10:30:00] '
                    '"GET /api/users" 200 1234'},
        {"message": '5.6.7.8 - bob [15/Nov/2023:10:31:00] '
                    '"POST /api/orders" 500 88'},
    ])
    assert n == 2
    res = inst.sql(
        "SELECT ip, method, path, size FROM access_logs ORDER BY ts"
    )
    assert res.rows() == [
        ["1.2.3.4", "get", "/api/users", 1234],
        ["5.6.7.8", "post", "/api/orders", 88],
    ]
    sem = {r[0]: r[5] for r in inst.sql("DESCRIBE TABLE access_logs").rows()}
    assert sem["ip"] == "TAG" and sem["path"] == "FIELD"


def test_identity_pipeline(inst):
    mgr = PipelineManager.get(inst)
    n = mgr.ingest("public", "app_logs", "greptime_identity", [
        {"level": "error", "message": "boom", "code": 7},
        {"level": "info", "message": "ok"},
    ])
    assert n == 2
    res = inst.sql("SELECT level, message, code FROM app_logs "
                   "ORDER BY level")
    rows = res.rows()
    assert rows[0][:2] == ["error", "boom"] and rows[0][2] == 7
    assert rows[1][2] is None


def test_pipeline_persists(tmp_path):
    inst = Standalone(str(tmp_path / "d"))
    PipelineManager.get(inst).upsert_pipeline("p1", ACCESS_LOG_PIPELINE)
    inst.close()

    inst2 = Standalone(str(tmp_path / "d"))
    assert PipelineManager.get(inst2).pipeline_names() == ["p1"]
    inst2.close()



# ----------------------------------------------------------------------
# script engine
# ----------------------------------------------------------------------

def test_script_over_query(inst):
    inst.sql("CREATE TABLE m (host STRING, cpu DOUBLE, mem DOUBLE, "
             "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
    inst.sql("INSERT INTO m VALUES ('a', 10.0, 50.0, 1000), "
             "('b', 30.0, 70.0, 2000)")
    eng = PyEngine(inst)
    eng.insert_script("load", '''
@copr(args=["cpu", "mem"], returns=["load"],
      sql="SELECT cpu, mem FROM m ORDER BY host")
def load(cpu, mem):
    return cpu * 0.6 + mem * 0.4
''')
    res = eng.run_script("load")
    assert res.names == ["load"]
    np.testing.assert_allclose(
        np.asarray(res.cols[0].values, dtype=np.float64), [26.0, 46.0]
    )


def test_script_jax_math(inst):
    eng = PyEngine(inst)
    eng.insert_script("gen", '''
@copr(args=[], returns=["x", "y"])
def gen():
    x = jnp.arange(4.0)
    return x, jnp.sqrt(x)
''')
    res = eng.run_script("gen")
    assert res.names == ["x", "y"]
    np.testing.assert_allclose(res.cols[1].values, np.sqrt(np.arange(4.0)))


def test_script_persists(tmp_path):
    inst = Standalone(str(tmp_path / "d"))
    PyEngine(inst).insert_script("s1", '''
@copr(args=[], returns=["one"])
def one():
    return np.asarray([1.0])
''')
    inst.close()
    inst2 = Standalone(str(tmp_path / "d"))
    eng = PyEngine(inst2)
    assert eng.script_names() == ["s1"]
    assert eng.run_script("s1").rows() == [[1.0]]
    inst2.close()


# ----------------------------------------------------------------------
# metric engine
# ----------------------------------------------------------------------

def test_metric_engine_logical_tables(inst):
    inst.sql(
        "CREATE TABLE http_requests (host STRING, greptime_value DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) ENGINE=metric"
    )
    inst.sql(
        "CREATE TABLE grpc_requests (service STRING, greptime_value DOUBLE,"
        " ts TIMESTAMP TIME INDEX, PRIMARY KEY (service)) ENGINE=metric"
    )
    inst.sql("INSERT INTO http_requests VALUES ('a', 1.0, 1000), "
             "('b', 2.0, 1000)")
    inst.sql("INSERT INTO grpc_requests VALUES ('s1', 10.0, 1000)")
    # isolation: each logical table sees only its rows
    assert inst.sql("SELECT count(*) FROM http_requests").rows() == [[2]]
    assert inst.sql("SELECT count(*) FROM grpc_requests").rows() == [[1]]
    res = inst.sql(
        "SELECT host, greptime_value FROM http_requests ORDER BY host"
    )
    assert res.rows() == [["a", 1.0], ["b", 2.0]]
    # both share ONE physical table
    phys = inst.catalog.table("public", "greptime_physical_table")
    assert phys.row_count() == 3


def test_metric_engine_survives_restart(tmp_path):
    inst = Standalone(str(tmp_path / "d"))
    inst.sql(
        "CREATE TABLE m1 (host STRING, greptime_value DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host)) ENGINE=metric"
    )
    inst.sql("INSERT INTO m1 VALUES ('x', 5.0, 1000)")
    inst.close()
    inst2 = Standalone(str(tmp_path / "d"))
    assert inst2.sql("SELECT greptime_value FROM m1").rows() == [[5.0]]
    inst2.close()


# ----------------------------------------------------------------------
# COPY TO / FROM
# ----------------------------------------------------------------------

@pytest.mark.parametrize("fmt", ["parquet", "csv"])
def test_copy_roundtrip(inst, tmp_path, fmt):
    inst.sql("CREATE TABLE src (host STRING, v DOUBLE, "
             "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
    inst.sql("INSERT INTO src VALUES ('a', 1.5, 1000), ('b', 2.5, 2000)")
    path = str(tmp_path / f"out.{fmt}")
    out = inst.sql(f"COPY src TO '{path}' WITH (format = '{fmt}')")
    inst.sql("CREATE TABLE dst (host STRING, v DOUBLE, "
             "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
    inst.sql(f"COPY dst FROM '{path}' WITH (format = '{fmt}')")
    res = inst.sql("SELECT host, v, ts FROM dst ORDER BY host")
    assert res.rows() == [["a", 1.5, 1000], ["b", 2.5, 2000]]


# ----------------------------------------------------------------------
# fulltext matches
# ----------------------------------------------------------------------

def test_eval_matches():
    vals = np.asarray([
        "Connection timeout on server-1",
        "disk full on server-2",
        "connection refused quickly",
    ], object)
    assert list(eval_matches(vals, "connection")) == [True, False, True]
    assert list(eval_matches(vals, "connection AND timeout")) == [
        True, False, False,
    ]
    assert list(eval_matches(vals, "timeout OR disk")) == [
        True, True, False,
    ]
    assert list(eval_matches(vals, "connection NOT refused")) == [
        True, False, False,
    ]
    assert list(eval_matches(vals, '"disk full"')) == [False, True, False]


def test_matches_in_sql(inst):
    inst.sql("CREATE TABLE logs (app STRING, message STRING, "
             "ts TIMESTAMP TIME INDEX, PRIMARY KEY (app))")
    inst.sql(
        "INSERT INTO logs VALUES "
        "('web', 'connection timeout to db', 1000), "
        "('web', 'request ok', 2000), "
        "('db', 'disk full error', 3000)"
    )
    res = inst.sql(
        "SELECT message FROM logs WHERE matches(message, "
        "'timeout OR \"disk full\"') ORDER BY ts"
    )
    assert res.rows() == [["connection timeout to db"], ["disk full error"]]


# ----------------------------------------------------------------------
# auth
# ----------------------------------------------------------------------

def test_http_basic_auth(tmp_path):
    from greptimedb_tpu.auth import StaticUserProvider
    from greptimedb_tpu.servers.http import HttpServer

    inst = Standalone(str(tmp_path / "d"))
    provider = StaticUserProvider({"admin": "secret"})
    srv = HttpServer(inst, port=0, user_provider=provider).start()
    try:
        import base64
        import urllib.error

        url = f"http://127.0.0.1:{srv.port}/v1/sql"
        body = b"sql=SELECT 1"
        headers = {"Content-Type": "application/x-www-form-urlencoded"}
        # no credentials -> 401
        try:
            urllib.request.urlopen(
                urllib.request.Request(url, body, headers, method="POST")
            )
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        # valid credentials -> 200
        tok = base64.b64encode(b"admin:secret").decode()
        req = urllib.request.Request(
            url, body, {**headers, "Authorization": f"Basic {tok}"},
            method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
        # health stays open
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/health"
        ) as resp:
            assert resp.status == 200
    finally:
        srv.stop()
        inst.close()


# ----------------------------------------------------------------------
# log ingest over HTTP (events endpoint)
# ----------------------------------------------------------------------

def test_http_log_ingest(tmp_path):
    from greptimedb_tpu.servers.http import HttpServer

    inst = Standalone(str(tmp_path / "d"))
    srv = HttpServer(inst, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # upload pipeline
        req = urllib.request.Request(
            f"{base}/v1/events/pipelines/access",
            ACCESS_LOG_PIPELINE.encode(), method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
        # ingest logs
        payload = json.dumps([{
            "message": '9.9.9.9 - eve [15/Nov/2023:10:32:00] '
                       '"GET /login" 401 0'
        }]).encode()
        req = urllib.request.Request(
            f"{base}/v1/events/logs?db=public&table=weblogs"
            f"&pipeline_name=access",
            payload, {"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            assert json.loads(resp.read())["rows"] == 1
        res = inst.sql("SELECT ip, status FROM weblogs")
        assert res.rows() == [["9.9.9.9", "401"]]
    finally:
        srv.stop()
        inst.close()
    


def test_explain_analyze_stage_metrics(tmp_path):
    """EXPLAIN ANALYZE reports per-stage metrics (VERDICT r2 task #9):
    rows scanned, exec path, cache state, reduce/device timings."""
    import numpy as np

    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path / "data"))
    inst.sql(
        "CREATE TABLE ea (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "ea")
    table.write(
        {"host": np.asarray(["a", "b"] * 10, object)},
        np.arange(20, dtype=np.int64) * 1000,
        {"v": np.arange(20, dtype=np.float64)},
    )
    r = inst.sql("EXPLAIN ANALYZE SELECT host, count(*) FROM ea GROUP BY host")
    text = "\n".join(row[0] for row in r.rows())
    assert "rows_scanned: 20" in text
    assert "agg_groups: 2" in text
    assert "exec_path_aggregate:" in text
    assert "reduce_ms:" in text
    # joins report their stage too
    r = inst.sql(
        "EXPLAIN ANALYZE SELECT a.host FROM ea a JOIN ea b ON a.host = b.host"
    )
    text = "\n".join(row[0] for row in r.rows())
    assert "join_rows:" in text and "join_ms:" in text
    inst.close()
