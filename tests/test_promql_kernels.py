"""PromQL function kernels vs a straight-line numpy port of Prometheus
semantics (functions.go extrapolatedRate et al)."""

import numpy as np
import jax.numpy as jnp
import pytest

from greptimedb_tpu.ops import grid as G
from greptimedb_tpu.ops import promql as P
from greptimedb_tpu.ops import window as W


def ref_extrapolated_rate(ts_ms, vals, t_end_ms, range_ms, is_counter, is_rate):
    """Numpy reference for Prometheus extrapolatedRate."""
    t_start_ms = t_end_ms - range_ms
    sel = (ts_ms > t_start_ms) & (ts_ms <= t_end_ms)
    ts_w, v_w = ts_ms[sel], vals[sel]
    if len(ts_w) < 2:
        return None
    result = v_w[-1] - v_w[0]
    if is_counter:
        for a, b in zip(v_w[:-1], v_w[1:]):
            if b < a:
                result += a
    dur_start = (ts_w[0] - t_start_ms) / 1000.0
    dur_end = (t_end_ms - ts_w[-1]) / 1000.0
    sampled = (ts_w[-1] - ts_w[0]) / 1000.0
    avg_dur = sampled / (len(ts_w) - 1)
    if is_counter and result > 0 and v_w[0] >= 0:
        dur_zero = sampled * (v_w[0] / result)
        dur_start = min(dur_start, dur_zero)
    thresh = avg_dur * 1.1
    extr = sampled
    extr += dur_start if dur_start < thresh else avg_dur / 2
    extr += dur_end if dur_end < thresh else avg_dur / 2
    factor = extr / sampled
    out = result * factor
    if is_rate:
        out /= range_ms / 1000.0
    return out


def build(rng, *, reset=False, s=4, points=150):
    t0 = 1_700_000_000_000
    rows = []
    for sid in range(s):
        ts = t0 + np.arange(points) * 10_000 + sid * 1000
        keep = rng.random(points) > 0.2
        ts = ts[keep]
        inc = rng.random(keep.sum()) * 5
        vals = np.cumsum(inc)
        if reset:
            # inject counter resets
            cut = len(vals) // 2
            vals[cut:] = np.cumsum(inc[cut:])
        rows.extend((sid, int(t), float(v)) for t, v in zip(ts, vals))
    rows.sort()
    sid = np.array([r[0] for r in rows], dtype=np.int32)
    ts = np.array([r[1] for r in rows], dtype=np.int64)
    val = np.array([r[2] for r in rows], dtype=np.float64)

    start = t0 + 400_000
    end = t0 + 1_200_000
    step, range_ms = 30_000, 120_000
    spec, windows = W.plan_grid_and_windows(start, end, step, range_ms,
                                            data_interval_ms=1000)
    cell = spec.cell_of(ts).astype(np.int32)
    tsr = spec.device_ts(ts)
    vals_g, has, tsg = G.gridify(
        jnp.array(sid), jnp.array(cell), jnp.array(tsr), jnp.array(val),
        jnp.array(np.ones(len(sid), bool)), s, spec.num_cells,
    )
    steps_ms = np.arange(start, end + 1, step)
    return (sid, ts, val), spec, windows, (vals_g, has, tsg), steps_ms, range_ms


@pytest.mark.parametrize("fn,is_counter,is_rate", [
    ("rate", True, True), ("increase", True, False), ("delta", False, False),
])
@pytest.mark.parametrize("reset", [False, True])
def test_extrapolated_rate(rng, fn, is_counter, is_rate, reset):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng, reset=reset)
    sid, ts, val = rows
    out, present = P.eval_range_function(fn, *gridded, windows, spec)
    out, present = np.asarray(out), np.asarray(present)
    checked = 0
    for s in range(4):
        m = sid == s
        for j, t_end in enumerate(steps_ms):
            want = ref_extrapolated_rate(ts[m], val[m], t_end, range_ms,
                                         is_counter, is_rate)
            if want is None:
                assert not present[s, j]
            else:
                assert present[s, j]
                np.testing.assert_allclose(out[s, j], want, rtol=1e-9)
                checked += 1
    assert checked > 50


def test_changes_resets(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng, reset=True)
    sid, ts, val = rows
    for fn in ("changes", "resets"):
        out, present = P.eval_range_function(fn, *gridded, windows, spec)
        out = np.asarray(out)
        for s in range(4):
            m = sid == s
            for j, t_end in enumerate(steps_ms):
                sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
                wv = val[m][sel]
                if len(wv) == 0:
                    continue
                pairs = list(zip(wv[:-1], wv[1:]))
                if fn == "changes":
                    want = sum(1 for a, b in pairs if b != a)
                else:
                    want = sum(1 for a, b in pairs if b < a)
                np.testing.assert_allclose(out[s, j], want)


def test_idelta_irate(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng)
    sid, ts, val = rows
    for fn in ("idelta", "irate"):
        out, present = P.eval_range_function(fn, *gridded, windows, spec)
        out, present = np.asarray(out), np.asarray(present)
        for s in range(4):
            m = sid == s
            for j, t_end in enumerate(steps_ms):
                sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
                wts, wv = ts[m][sel], val[m][sel]
                if len(wv) < 2:
                    assert not present[s, j]
                    continue
                assert present[s, j]
                if fn == "idelta":
                    want = wv[-1] - wv[-2]
                else:
                    dv = wv[-1] if wv[-1] < wv[-2] else wv[-1] - wv[-2]
                    want = dv / ((wts[-1] - wts[-2]) / 1000.0)
                np.testing.assert_allclose(out[s, j], want, rtol=1e-9)


def test_deriv_predict_linear(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng)
    sid, ts, val = rows
    out, present = P.eval_range_function("deriv", *gridded, windows, spec)
    pred, _ = P.eval_range_function(
        "predict_linear", *gridded, windows, spec, args=(600.0,)
    )
    out, pred, present = np.asarray(out), np.asarray(pred), np.asarray(present)
    for s in range(4):
        m = sid == s
        for j, t_end in enumerate(steps_ms):
            sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
            wts, wv = ts[m][sel], val[m][sel]
            if len(wv) < 2:
                assert not present[s, j]
                continue
            t = (wts - t_end) / 1000.0
            slope, intercept = np.polyfit(t, wv, 1)
            np.testing.assert_allclose(out[s, j], slope, rtol=1e-6)
            np.testing.assert_allclose(
                pred[s, j], intercept + slope * 600.0, rtol=1e-6
            )


def test_holt_winters(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng)
    sid, ts, val = rows
    sf, tf = 0.3, 0.2
    out, present = P.eval_range_function(
        "holt_winters", *gridded, windows, spec, args=(sf, tf)
    )
    out, present = np.asarray(out), np.asarray(present)
    for s in range(4):
        m = sid == s
        for j, t_end in enumerate(steps_ms[::4]):
            jj = j * 4
            sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
            wv = val[m][sel]
            if len(wv) < 2:
                assert not present[s, jj]
                continue
            sm, b = wv[1], wv[1] - wv[0]
            for x in wv[2:]:
                prev = sm
                sm = sf * x + (1 - sf) * (sm + b)
                b = tf * (sm - prev) + (1 - tf) * b
            np.testing.assert_allclose(out[s, jj], sm, rtol=1e-9)


def test_aggr_over_time_family(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng)
    sid, ts, val = rows
    fams = {
        "sum_over_time": np.sum, "avg_over_time": np.mean,
        "min_over_time": np.min, "max_over_time": np.max,
        "stddev_over_time": lambda x: np.std(x),
        "stdvar_over_time": lambda x: np.var(x),
        "last_over_time": lambda x: x[-1],
        "count_over_time": len,
    }
    for fn, ref in fams.items():
        out, present = P.eval_range_function(fn, *gridded, windows, spec)
        out, present = np.asarray(out), np.asarray(present)
        for s in range(4):
            m = sid == s
            for j, t_end in enumerate(steps_ms[::5]):
                jj = j * 5
                sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
                wv = val[m][sel]
                if len(wv) == 0:
                    assert not present[s, jj], fn
                    continue
                np.testing.assert_allclose(
                    out[s, jj], ref(wv), rtol=1e-8, err_msg=fn
                )


def test_quantile_over_time(rng):
    rows, spec, windows, gridded, steps_ms, range_ms = build(rng)
    sid, ts, val = rows
    out, present = P.eval_range_function(
        "quantile_over_time", *gridded, windows, spec, args=(0.9,)
    )
    out = np.asarray(out)
    for s in range(4):
        m = sid == s
        for j, t_end in enumerate(steps_ms[::5]):
            jj = j * 5
            sel = (ts[m] > t_end - range_ms) & (ts[m] <= t_end)
            wv = val[m][sel]
            if len(wv):
                np.testing.assert_allclose(
                    out[s, jj], np.quantile(wv, 0.9), rtol=1e-9
                )


def test_histogram_quantile():
    le = jnp.array([0.1, 0.5, 1.0, np.inf])
    # one histogram: 10 obs <= 0.1, 30 <= 0.5, 60 <= 1.0, 100 total
    buckets = jnp.array([[10.0, 30.0, 60.0, 100.0]])
    mask = jnp.ones((1, 4), dtype=bool)
    out, ok = P.histogram_quantile(le, buckets, mask, 0.5)
    # rank = 50 -> bucket 2 (0.5, 1.0], frac = (50-30)/30
    want = 0.5 + (1.0 - 0.5) * (50 - 30) / 30
    np.testing.assert_allclose(np.asarray(out)[0], want, rtol=1e-12)
    assert bool(np.asarray(ok)[0])
    # q=0.05 -> rank 5 in first bucket, interpolate from 0
    out, _ = P.histogram_quantile(le, buckets, mask, 0.05)
    np.testing.assert_allclose(np.asarray(out)[0], 0.1 * 5 / 10, rtol=1e-12)
    # q in +inf bucket -> highest finite bound
    out, _ = P.histogram_quantile(le, buckets, mask, 0.99)
    np.testing.assert_allclose(np.asarray(out)[0], 1.0)


def test_aggregate_across_series(rng):
    s, j, g = 12, 7, 3
    vals = jnp.array(rng.normal(size=(s, j)))
    present = jnp.array(rng.random((s, j)) > 0.3)
    gids = jnp.array(rng.integers(0, g, s).astype(np.int32))
    for op in ("sum", "avg", "min", "max", "count", "stddev"):
        out, ok = P.aggregate_across_series(vals, present, gids, g, op)
        out, ok = np.asarray(out), np.asarray(ok)
        vn, pn, gn = np.asarray(vals), np.asarray(present), np.asarray(gids)
        for gi in range(g):
            for jj in range(j):
                col = vn[(gn == gi), jj]
                m = pn[(gn == gi), jj]
                sel = col[m]
                if len(sel) == 0:
                    assert not ok[gi, jj]
                    continue
                ref = {
                    "sum": np.sum, "avg": np.mean, "min": np.min,
                    "max": np.max, "count": len, "stddev": np.std,
                }[op](sel)
                np.testing.assert_allclose(out[gi, jj], ref, rtol=1e-9,
                                           err_msg=op)
