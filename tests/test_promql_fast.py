"""PromQL selector-grid fast path: equivalence with the generic engine,
cache invalidation, and fallback behavior (VERDICT r2 task #2)."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.promql import fast as F
from greptimedb_tpu.promql.engine import PromEngine, VectorValue

T0 = 1_700_000_000_000


@pytest.fixture()
def inst(tmp_path):
    F.invalidate_cache()
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()
    F.invalidate_cache()


def setup_metrics(inst, *, n_hosts=6, n=41, step_ms=15_000):
    inst.sql(
        "CREATE TABLE req_total (host STRING, dc STRING, "
        "greptime_value DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host, dc))"
    )
    table = inst.catalog.table("public", "req_total")
    ts = T0 + np.arange(n) * step_ms
    rng = np.random.default_rng(7)
    for h in range(n_hosts):
        vals = np.cumsum(rng.uniform(0, 5, n))
        table.write(
            {"host": np.full(n, f"h{h}", object),
             "dc": np.full(n, f"dc{h % 2}", object)},
            ts,
            {"greptime_value": vals},
        )
    return ts


def run_both(inst, promql, start, end, step):
    eng = PromEngine(inst)
    fast_val, ev = eng.query_range(promql, start, end, step)

    real = F.try_fast
    F_disabled = lambda *a, **k: None  # noqa: E731
    F.try_fast = F_disabled
    try:
        slow_val, _ = PromEngine(inst).query_range(promql, start, end, step)
    finally:
        F.try_fast = real
    return fast_val, slow_val, ev


def as_map(v: VectorValue):
    out = {}
    for i, lab in enumerate(v.labels):
        key = tuple(sorted(lab.items()))
        out[key] = (v.values[i], v.present[i])
    return out


QUERIES = [
    "sum by (host) (rate(req_total[1m]))",
    "sum(rate(req_total[1m]))",
    "avg by (dc) (increase(req_total[2m]))",
    "max by (dc) (delta(req_total[1m]))",
    "count by (dc) (req_total)",
    "sum by (host) (last_over_time(req_total[1m]))",
    "stddev by (dc) (rate(req_total[1m]))",
    'sum by (dc) (rate(req_total{host=~"h[0-2]"}[1m]))',
    'sum by (host) (rate(req_total{dc="dc0"}[1m]))',
    "sum by (host) (rate(req_total[1m] offset 1m))",
    "sum without (host) (changes(req_total[2m]))",
    "group by (dc) (req_total)",
]


@pytest.mark.parametrize("promql", QUERIES)
def test_fast_matches_generic(inst, promql):
    setup_metrics(inst)
    fast_val, slow_val, _ = run_both(
        inst, promql, T0 + 120_000, T0 + 480_000, 30_000
    )
    assert isinstance(fast_val, VectorValue)
    fm, sm = as_map(fast_val), as_map(slow_val)
    # generic path may emit all-absent series the fast path drops
    sm = {k: v for k, v in sm.items() if v[1].any()}
    assert set(fm) == set(sm), (promql, set(fm) ^ set(sm))
    for key in fm:
        fv, fp = fm[key]
        sv, sp = sm[key]
        np.testing.assert_array_equal(fp, sp, err_msg=promql)
        np.testing.assert_allclose(
            np.where(fp, fv, 0), np.where(sp, sv, 0),
            rtol=1e-5, atol=1e-6, err_msg=promql,
        )


def test_fast_path_taken_and_invalidated(inst):
    ts = setup_metrics(inst)
    eng = PromEngine(inst)
    v1, _ = eng.query_range(
        "sum by (host) (rate(req_total[1m]))",
        T0 + 120_000, T0 + 480_000, 30_000,
    )
    # the cache now holds one entry for (req_total, greptime_value)
    assert any(
        e.num_series > 0 for e in F._CACHE._entries.values()
    ), "fast path did not build a grid entry"
    # new write must invalidate: append a big spike to h0 and re-query
    table = inst.catalog.table("public", "req_total")
    t_new = int(ts[-1]) + 15_000
    table.write(
        {"host": np.asarray(["h0"], object), "dc": np.asarray(["dc0"], object)},
        np.asarray([t_new], np.int64),
        {"greptime_value": np.asarray([1e9])},
    )
    v2, _ = eng.query_range(
        "sum by (host) (rate(req_total[1m]))",
        T0 + 120_000, t_new, 15_000,
    )
    h0 = [i for i, l in enumerate(v2.labels) if l.get("host") == "h0"][0]
    assert v2.values[h0][-1] > 1e5, "stale grid served after write"


def test_unaligned_step_falls_back(inst):
    setup_metrics(inst)
    # step 7s does not divide the 15s data interval: generic path must serve
    eng = PromEngine(inst)
    real = F._fused_query
    called = []
    F._fused_query = lambda *a, **k: called.append(1) or real(*a, **k)
    try:
        val, _ = eng.query_range(
            "sum by (host) (rate(req_total[1m]))",
            T0 + 120_000, T0 + 180_000, 7_000,
        )
    finally:
        F._fused_query = real
    assert not called
    assert isinstance(val, VectorValue) and val.num_series > 0


def test_no_match_returns_empty(inst):
    setup_metrics(inst)
    eng = PromEngine(inst)
    val, _ = eng.query_range(
        'sum by (host) (rate(req_total{host="nope"}[1m]))',
        T0 + 120_000, T0 + 180_000, 30_000,
    )
    assert val.num_series == 0


def test_matcher_mask_vectorized_semantics(inst):
    """SeriesRegistry.match_mask equals the per-series semantics of the old
    match_sids loop, including missing-tag and regex cases."""
    import re

    setup_metrics(inst)
    table = inst.catalog.table("public", "req_total")
    reg = table.regions[0].series
    cases = [
        [("host", "eq", "h1")],
        [("host", "ne", "h1")],
        [("host", "re", re.compile("h[0-2]"))],
        [("host", "nre", re.compile("h[0-2]")), ("dc", "eq", "dc1")],
        [("missing", "eq", "")],
        [("missing", "eq", "x")],
        [("host", "in", ["h1", "h3"])],
    ]
    for matchers in cases:
        mask = reg.match_mask(matchers)
        sids = reg.match_sids(matchers)
        expect = []
        for sid in range(reg.num_series):
            tags = reg.series_tags(sid)
            ok = True
            for name, op, value in matchers:
                v = tags.get(name, "")
                if op == "eq":
                    ok &= v == value
                elif op == "ne":
                    ok &= v != value
                elif op == "in":
                    ok &= v in value
                elif op == "re":
                    ok &= bool(value.fullmatch(v))
                elif op == "nre":
                    ok &= not value.fullmatch(v)
            expect.append(ok)
        np.testing.assert_array_equal(mask, np.asarray(expect), err_msg=str(matchers))
        np.testing.assert_array_equal(sids, np.nonzero(expect)[0])
