"""PromQL selector-grid fast path: equivalence with the generic engine,
cache invalidation, and fallback behavior (VERDICT r2 task #2)."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.promql import fast as F
from greptimedb_tpu.promql.engine import PromEngine, VectorValue

T0 = 1_700_000_000_000


@pytest.fixture()
def inst(tmp_path):
    F.invalidate_cache()
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()
    F.invalidate_cache()


def setup_metrics(inst, *, n_hosts=6, n=41, step_ms=15_000):
    inst.sql(
        "CREATE TABLE req_total (host STRING, dc STRING, "
        "greptime_value DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host, dc))"
    )
    table = inst.catalog.table("public", "req_total")
    ts = T0 + np.arange(n) * step_ms
    rng = np.random.default_rng(7)
    for h in range(n_hosts):
        vals = np.cumsum(rng.uniform(0, 5, n))
        table.write(
            {"host": np.full(n, f"h{h}", object),
             "dc": np.full(n, f"dc{h % 2}", object)},
            ts,
            {"greptime_value": vals},
        )
    return ts


def run_both(inst, promql, start, end, step):
    eng = PromEngine(inst)
    fast_val, ev = eng.query_range(promql, start, end, step)

    real = F.try_fast
    F_disabled = lambda *a, **k: None  # noqa: E731
    F.try_fast = F_disabled
    try:
        slow_val, _ = PromEngine(inst).query_range(promql, start, end, step)
    finally:
        F.try_fast = real
    return fast_val, slow_val, ev


def as_map(v: VectorValue):
    out = {}
    for i, lab in enumerate(v.labels):
        key = tuple(sorted(lab.items()))
        out[key] = (v.values[i], v.present[i])
    return out


QUERIES = [
    "sum by (host) (rate(req_total[1m]))",
    "sum(rate(req_total[1m]))",
    "avg by (dc) (increase(req_total[2m]))",
    "max by (dc) (delta(req_total[1m]))",
    "count by (dc) (req_total)",
    "sum by (host) (last_over_time(req_total[1m]))",
    "stddev by (dc) (rate(req_total[1m]))",
    'sum by (dc) (rate(req_total{host=~"h[0-2]"}[1m]))',
    'sum by (host) (rate(req_total{dc="dc0"}[1m]))',
    "sum by (host) (rate(req_total[1m] offset 1m))",
    "sum without (host) (changes(req_total[2m]))",
    "group by (dc) (req_total)",
]


@pytest.mark.parametrize("promql", QUERIES)
def test_fast_matches_generic(inst, promql):
    setup_metrics(inst)
    fast_val, slow_val, _ = run_both(
        inst, promql, T0 + 120_000, T0 + 480_000, 30_000
    )
    assert isinstance(fast_val, VectorValue)
    fm, sm = as_map(fast_val), as_map(slow_val)
    # generic path may emit all-absent series the fast path drops
    sm = {k: v for k, v in sm.items() if v[1].any()}
    assert set(fm) == set(sm), (promql, set(fm) ^ set(sm))
    for key in fm:
        fv, fp = fm[key]
        sv, sp = sm[key]
        np.testing.assert_array_equal(fp, sp, err_msg=promql)
        np.testing.assert_allclose(
            np.where(fp, fv, 0), np.where(sp, sv, 0),
            rtol=1e-5, atol=1e-6, err_msg=promql,
        )


def test_fast_path_taken_and_invalidated(inst):
    ts = setup_metrics(inst)
    eng = PromEngine(inst)
    v1, _ = eng.query_range(
        "sum by (host) (rate(req_total[1m]))",
        T0 + 120_000, T0 + 480_000, 30_000,
    )
    # the cache now holds one entry for (req_total, greptime_value)
    assert any(
        e.num_series > 0 for e in F._CACHE._entries.values()
    ), "fast path did not build a grid entry"
    # new write must invalidate: append a big spike to h0 and re-query
    table = inst.catalog.table("public", "req_total")
    t_new = int(ts[-1]) + 15_000
    table.write(
        {"host": np.asarray(["h0"], object), "dc": np.asarray(["dc0"], object)},
        np.asarray([t_new], np.int64),
        {"greptime_value": np.asarray([1e9])},
    )
    v2, _ = eng.query_range(
        "sum by (host) (rate(req_total[1m]))",
        T0 + 120_000, t_new, 15_000,
    )
    h0 = [i for i, l in enumerate(v2.labels) if l.get("host") == "h0"][0]
    assert v2.values[h0][-1] > 1e5, "stale grid served after write"


def test_unaligned_step_falls_back(inst):
    setup_metrics(inst)
    # step 7s does not divide the 15s data interval: generic path must serve
    eng = PromEngine(inst)
    real = F._fused_query
    called = []
    F._fused_query = lambda *a, **k: called.append(1) or real(*a, **k)
    try:
        val, _ = eng.query_range(
            "sum by (host) (rate(req_total[1m]))",
            T0 + 120_000, T0 + 180_000, 7_000,
        )
    finally:
        F._fused_query = real
    assert not called
    assert isinstance(val, VectorValue) and val.num_series > 0


def test_no_match_returns_empty(inst):
    setup_metrics(inst)
    eng = PromEngine(inst)
    val, _ = eng.query_range(
        'sum by (host) (rate(req_total{host="nope"}[1m]))',
        T0 + 120_000, T0 + 180_000, 30_000,
    )
    assert val.num_series == 0


def test_matcher_mask_vectorized_semantics(inst):
    """SeriesRegistry.match_mask equals the per-series semantics of the old
    match_sids loop, including missing-tag and regex cases."""
    import re

    setup_metrics(inst)
    table = inst.catalog.table("public", "req_total")
    reg = table.regions[0].series
    cases = [
        [("host", "eq", "h1")],
        [("host", "ne", "h1")],
        [("host", "re", re.compile("h[0-2]"))],
        [("host", "nre", re.compile("h[0-2]")), ("dc", "eq", "dc1")],
        [("missing", "eq", "")],
        [("missing", "eq", "x")],
        [("host", "in", ["h1", "h3"])],
    ]
    for matchers in cases:
        mask = reg.match_mask(matchers)
        sids = reg.match_sids(matchers)
        expect = []
        for sid in range(reg.num_series):
            tags = reg.series_tags(sid)
            ok = True
            for name, op, value in matchers:
                v = tags.get(name, "")
                if op == "eq":
                    ok &= v == value
                elif op == "ne":
                    ok &= v != value
                elif op == "in":
                    ok &= v in value
                elif op == "re":
                    ok &= bool(value.fullmatch(v))
                elif op == "nre":
                    ok &= not value.fullmatch(v)
            expect.append(ok)
        np.testing.assert_array_equal(mask, np.asarray(expect), err_msg=str(matchers))
        np.testing.assert_array_equal(sids, np.nonzero(expect)[0])


def _mk_histogram(tmp_path, n_groups=6, les=("0.1", "0.5", "1", "+Inf")):
    import tempfile

    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path), prefer_device=True,
                      warm_start=False)
    inst.execute_sql(
        "create table lat_bucket (ts timestamp time index, host string, "
        "le string, greptime_value double, primary key (host, le))"
    )
    tab = inst.catalog.table("public", "lat_bucket")
    rng = np.random.default_rng(5)
    rows_h, rows_l, rows_t, rows_v = [], [], [], []
    counts = {f"h{i}": np.zeros(len(les)) for i in range(n_groups)}
    for s in range(6):
        for h in counts:
            counts[h] = counts[h] + np.sort(
                rng.integers(0, 5, size=len(les))
            ).cumsum()
            for bi, le in enumerate(les):
                rows_h.append(h)
                rows_l.append(le)
                rows_t.append(s * 10_000)
                rows_v.append(float(counts[h][bi]))
    tab.write(
        {"host": np.asarray(rows_h, object),
         "le": np.asarray(rows_l, object)},
        np.asarray(rows_t, np.int64),
        {"greptime_value": np.asarray(rows_v)},
    )
    return inst


def _canon(v):
    order = sorted(range(len(v.labels)),
                   key=lambda i: sorted(v.labels[i].items()))
    return [
        (sorted(v.labels[i].items()),
         np.where(v.present[i], np.round(v.values[i], 6), None).tolist())
        for i in order
    ]


def test_fast_histogram_quantile_matches_generic(tmp_path):
    """histogram_quantile rides the selector-grid fast path (VERDICT r3
    missing #7) and must equal the generic engine exactly."""
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    inst = _mk_histogram(tmp_path / "d")
    try:
        q = "histogram_quantile(0.9, rate(lat_bucket[30s]))"
        eng = PromEngine(inst)
        args = (30_000, 50_000, 10_000)
        F.invalidate_cache()
        orig = F.try_fast_histogram
        F.try_fast_histogram = lambda *a, **k: None
        try:
            vg, _ = eng.query_range(q, *args)
        finally:
            F.try_fast_histogram = orig
        F.invalidate_cache()
        before = F._FAST_HITS.labels("hit").value
        vf, _ = eng.query_range(q, *args)
        assert F._FAST_HITS.labels("hit").value > before, (
            "histogram did not take the fast path"
        )
        assert _canon(vg) == _canon(vf)
        # instant (no range fn) shape too
        q2 = "histogram_quantile(0.5, lat_bucket)"
        F.invalidate_cache()
        F.try_fast_histogram = lambda *a, **k: None
        try:
            vg2, _ = eng.query_range(q2, *args)
        finally:
            F.try_fast_histogram = orig
        F.invalidate_cache()
        vf2, _ = eng.query_range(q2, *args)
        assert _canon(vg2) == _canon(vf2)
    finally:
        F.invalidate_cache()
        inst.close()


def test_fast_histogram_fallbacks(tmp_path):
    """No +Inf bucket or non-le tables must fall back, not mis-answer."""
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    inst = _mk_histogram(tmp_path / "d", les=("0.1", "0.5", "1"))
    try:
        q = "histogram_quantile(0.9, rate(lat_bucket[30s]))"
        F.invalidate_cache()
        v, _ = PromEngine(inst).query_range(q, 30_000, 50_000, 10_000)
        # Prometheus: histograms without +Inf are undefined -> empty
        assert v.num_series == 0
    finally:
        F.invalidate_cache()
        inst.close()


def test_fast_histogram_sum_by_matches_generic(tmp_path):
    """The at-scale shape: histogram_quantile over `sum by (le, svc)`
    of pod-level buckets — one fused program, equal to generic."""
    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    inst = Standalone(str(tmp_path / "d"), prefer_device=True,
                      warm_start=False)
    inst.execute_sql(
        "create table lb (ts timestamp time index, pod string, "
        "svc string, le string, greptime_value double, "
        "primary key (pod, svc, le))"
    )
    tab = inst.catalog.table("public", "lb")
    les = ["0.1", "0.5", "1", "+Inf"]
    rng = np.random.default_rng(5)
    rows = {"pod": [], "svc": [], "le": []}
    ts_l, v_l = [], []
    counts = {}
    for s in range(6):
        for p in range(12):
            pod, svc = f"p{p}", f"s{p % 3}"
            counts[pod] = counts.get(pod, np.zeros(4)) + np.sort(
                rng.integers(0, 5, 4)
            ).cumsum()
            for bi, le in enumerate(les):
                rows["pod"].append(pod)
                rows["svc"].append(svc)
                rows["le"].append(le)
                ts_l.append(s * 10_000)
                v_l.append(float(counts[pod][bi]))
    tab.write(
        {k: np.asarray(v, object) for k, v in rows.items()},
        np.asarray(ts_l, np.int64),
        {"greptime_value": np.asarray(v_l)},
    )
    try:
        q = "histogram_quantile(0.9, sum by (le, svc) (rate(lb[30s])))"
        eng = PromEngine(inst)
        args = (30_000, 50_000, 10_000)
        F.invalidate_cache()
        orig = F.try_fast_histogram
        F.try_fast_histogram = lambda *a, **k: None
        try:
            vg, _ = eng.query_range(q, *args)
        finally:
            F.try_fast_histogram = orig
        F.invalidate_cache()
        before = F._FAST_HITS.labels("hit").value
        vf, _ = eng.query_range(q, *args)
        assert F._FAST_HITS.labels("hit").value > before
        assert _canon(vg) == _canon(vf)
        assert vf.num_series == 3
    finally:
        F.invalidate_cache()
        inst.close()


# ----------------------------------------------------------------------
# round-5 fast paths: arg-taking range fns, topk/bottomk, vector<op>vector
# ----------------------------------------------------------------------

def run_both_all(inst, promql, start, end, step):
    """Query once with every fast path live, once with all of them
    disabled (resolution stubbed out) — results must agree."""
    eng = PromEngine(inst)
    fast_val, ev = eng.query_range(promql, start, end, step)
    real_resolve = F._resolve_fast_selector
    real_binary = F.try_fast_binary
    F._resolve_fast_selector = lambda *a, **k: None
    F.try_fast_binary = lambda *a, **k: None
    try:
        slow_val, _ = PromEngine(inst).query_range(promql, start, end,
                                                   step)
    finally:
        F._resolve_fast_selector = real_resolve
        F.try_fast_binary = real_binary
    return fast_val, slow_val, ev


def assert_equivalent(fast_val, slow_val, promql, *, rtol=1e-5):
    fm, sm = as_map(fast_val), as_map(slow_val)
    sm = {k: v for k, v in sm.items() if v[1].any()}
    fm = {k: v for k, v in fm.items() if v[1].any()}
    assert set(fm) == set(sm), (promql, set(fm) ^ set(sm))
    for key in fm:
        fv, fp = fm[key]
        sv, sp = sm[key]
        np.testing.assert_array_equal(fp, sp, err_msg=promql)
        np.testing.assert_allclose(
            np.where(fp, fv, 0), np.where(sp, sv, 0),
            rtol=rtol, atol=1e-5, err_msg=promql,
        )


ARG_FN_QUERIES = [
    "sum by (host) (quantile_over_time(0.9, req_total[2m]))",
    "max by (dc) (min_over_time(req_total[1m]))",
    "sum by (dc) (max_over_time(req_total[2m]))",
    "avg by (dc) (stddev_over_time(req_total[2m]))",
    "sum by (host) (deriv(req_total[2m]))",
    "sum by (host) (predict_linear(req_total[2m], 600))",
    "sum by (dc) (holt_winters(req_total[2m], 0.5, 0.5))",
    "sum by (dc) (mad_over_time(req_total[2m]))",
]


@pytest.mark.parametrize("promql", ARG_FN_QUERIES)
def test_arg_range_fns_fast_matches_generic(inst, promql):
    setup_metrics(inst)
    fast_val, slow_val, _ = run_both_all(
        inst, promql, T0 + 120_000, T0 + 480_000, 30_000
    )
    assert isinstance(fast_val, VectorValue)
    assert_equivalent(fast_val, slow_val, promql)


TOPK_QUERIES = [
    "topk(3, rate(req_total[1m]))",
    "bottomk(2, rate(req_total[1m]))",
    "topk(3, req_total)",
    "topk(100, rate(req_total[1m]))",  # k > num_series
    'topk(2, rate(req_total{dc="dc0"}[1m]))',
]


@pytest.mark.parametrize("promql", TOPK_QUERIES)
def test_topk_fast_matches_generic(inst, promql):
    setup_metrics(inst)
    fast_val, slow_val, _ = run_both_all(
        inst, promql, T0 + 120_000, T0 + 480_000, 30_000
    )
    assert isinstance(fast_val, VectorValue)
    assert_equivalent(fast_val, slow_val, promql)


def test_topk_uses_fused_kernel(inst):
    setup_metrics(inst)
    called = []
    real = F._fused_topk
    F._fused_topk = lambda *a, **k: called.append(1) or real(*a, **k)
    try:
        PromEngine(inst).query_range(
            "topk(2, rate(req_total[1m]))",
            T0 + 120_000, T0 + 240_000, 30_000,
        )
    finally:
        F._fused_topk = real
    assert called, "topk did not take the fused fast path"


BINARY_QUERIES = [
    "rate(req_total[1m]) / last_over_time(req_total[1m])",
    "rate(req_total[1m]) + rate(req_total[2m])",
    "req_total - last_over_time(req_total[1m])",
    "rate(req_total[1m]) > 0.5",                  # vector-scalar: generic
    "rate(req_total[1m]) > rate(req_total[2m])",  # filter comparison
    "rate(req_total[1m]) >= bool rate(req_total[2m])",
    'rate(req_total{dc="dc0"}[1m]) * rate(req_total[1m])',
    "sum by (dc) (rate(req_total[1m]) / last_over_time(req_total[1m]))",
    "avg by (host) (req_total + req_total)",
    "sum(rate(req_total[1m]) / last_over_time(req_total[1m]))",
]


@pytest.mark.parametrize("promql", BINARY_QUERIES)
def test_binary_fast_matches_generic(inst, promql):
    setup_metrics(inst)
    fast_val, slow_val, _ = run_both_all(
        inst, promql, T0 + 120_000, T0 + 480_000, 30_000
    )
    assert isinstance(fast_val, VectorValue)
    assert_equivalent(fast_val, slow_val, promql)


def test_binary_on_ignoring_falls_back(inst):
    """Explicit matching modifiers use the generic label matcher."""
    setup_metrics(inst)
    called = []
    real = F._fused_binary
    F._fused_binary = lambda *a, **k: called.append(1) or real(*a, **k)
    try:
        v, _ = PromEngine(inst).query_range(
            "rate(req_total[1m]) / on(host, dc) "
            "last_over_time(req_total[1m])",
            T0 + 120_000, T0 + 240_000, 30_000,
        )
    finally:
        F._fused_binary = real
    assert not called
    assert v.num_series > 0


def test_topk_keeps_infinite_samples(inst):
    """A present +Inf sample must win topk (and -Inf bottomk) rather
    than being confused with the absent-slot fill (code-review r5)."""
    inst.sql(
        "CREATE TABLE infm (host STRING PRIMARY KEY, "
        "greptime_value DOUBLE, ts TIMESTAMP TIME INDEX)"
    )
    table = inst.catalog.table("public", "infm")
    ts = T0 + np.arange(4) * 15_000
    for h, v in [("a", np.inf), ("b", 5.0), ("c", -np.inf)]:
        table.write({"host": np.full(4, h, object)}, ts,
                    {"greptime_value": np.full(4, v)})
    eng = PromEngine(inst)
    v, _ = eng.query_range("topk(1, infm)", T0 + 15_000, T0 + 45_000,
                           15_000)
    assert [l["host"] for l in v.labels] == ["a"]
    assert np.isposinf(v.values[v.present]).all()
    v, _ = eng.query_range("bottomk(1, infm)", T0 + 15_000,
                           T0 + 45_000, 15_000)
    assert [l["host"] for l in v.labels] == ["c"]
    assert np.isneginf(v.values[v.present]).all()
