"""Plain GROUP BY on the fused device program vs the host path.

One jit program computes every aggregate of the query and returns one
(rows, groups) matrix — one device->host transfer per GROUP BY (the
reference runs per-operator aggregate streams,
/root/reference/src/query/src/datafusion.rs:75).
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query.executor import QueryEngine


@pytest.fixture
def inst(tmp_path, rng):
    i = Standalone(str(tmp_path))
    i.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " dc string primary key, u double, v double)"
    )
    tab = i.catalog.table("public", "cpu")
    n_hosts, t = 20, 150
    base = 1_700_000_000_000  # epoch-ms: must survive the device exactly
    ts = (np.tile(np.arange(t) * 1000, n_hosts) + base).astype(np.int64)
    hosts = np.repeat([f"h{i:02d}" for i in range(n_hosts)], t).astype(object)
    dcs = np.repeat([f"d{i % 3}" for i in range(n_hosts)], t).astype(object)
    u = rng.random(n_hosts * t) * 100
    v = rng.random(n_hosts * t) * 10
    valid = rng.random(n_hosts * t) > 0.07
    tab.write({"host": hosts, "dc": dcs}, ts, {"u": u, "v": v},
              field_valid={"u": valid})
    yield i
    i.close()


QUERIES = [
    "SELECT host, count(*), sum(u), avg(u), min(v), max(v) FROM cpu "
    "GROUP BY host ORDER BY host",
    "SELECT dc, stddev(u), var_pop(v), count(u) FROM cpu "
    "GROUP BY dc ORDER BY dc",
    # TSBS lastpoint shape: last value per series by time
    "SELECT host, last_value(u), first_value(v) FROM cpu "
    "GROUP BY host ORDER BY host",
    "SELECT dc, last_value(v) FROM cpu GROUP BY dc ORDER BY dc",
    "SELECT count(*), avg(u), last_value(u) FROM cpu",
]


@pytest.mark.parametrize("q", QUERIES)
def test_groupby_device_matches_host(inst, q):
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device", q
    assert rh.num_rows == rd.num_rows
    for i in range(len(rh.names)):
        a, b = rh.cols[i], rd.cols[i]
        assert (a.valid_mask == b.valid_mask).all(), (q, rh.names[i])
        if a.values.dtype == object:
            assert (a.values == b.values).all(), (q, rh.names[i])
        else:
            m = a.valid_mask
            np.testing.assert_allclose(
                np.asarray(a.values, float)[m],
                np.asarray(b.values, float)[m],
                rtol=2e-4, atol=1e-3, err_msg=(q, rh.names[i]),
            )


def test_lastpoint_winner_is_exact_row(inst):
    """first/last on device must pick the exact (ts, row) winner, not a
    close value: compare at f32 precision for equality."""
    q = ("SELECT host, last_value(u), first_value(u) FROM cpu "
         "GROUP BY host ORDER BY host")
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device"
    for i in (1, 2):
        np.testing.assert_array_equal(
            np.asarray(rh.cols[i].values, np.float64).astype(np.float32),
            np.asarray(rd.cols[i].values, np.float64).astype(np.float32),
        )


def test_fallback_counter_exported(inst):
    from greptimedb_tpu.telemetry.metrics import global_registry

    inst.query_engine = QueryEngine(prefer_device=True)
    inst.sql("SELECT host, median(u) FROM cpu GROUP BY host")  # quantile
    assert inst.query_engine.last_exec_path == "host"
    text = global_registry.render()
    assert 'gtpu_query_exec_path_total{kind="aggregate",path="host:op"}' \
        in text
    inst.sql("SELECT host, avg(u) FROM cpu GROUP BY host")
    text = global_registry.render()
    assert 'gtpu_query_exec_path_total{kind="aggregate",path="device"}' \
        in text
