"""Compaction & tiered-storage dataplane (storage/compaction.py):
leveled TWCS picker, bounded pool, device-accelerated merge parity,
tombstone GC across merge sets, hot/cold tiering, orphan cleanup,
maintenance error isolation, ADMIN routing, cache invalidation."""

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.errors import TableNotFoundError
from greptimedb_tpu.storage.compaction import (
    CompactionOptions,
    CompactionScheduler,
    cleanup_orphan_ssts,
    compact_once,
    pick_compaction,
    pick_tasks,
    purge_expired,
    read_amplification,
)
from greptimedb_tpu.storage.device_merge import host_merge, merge_rows
from greptimedb_tpu.storage.engine import EngineConfig, TsdbEngine
from greptimedb_tpu.storage.memtable import (
    OP_DELETE,
    OP_PUT,
    ColumnarRows,
)
from greptimedb_tpu.storage.object_store import (
    FsObjectStore,
    MemoryObjectStore,
)
from greptimedb_tpu.storage.region import Region, RegionMetadata, RegionOptions
from greptimedb_tpu.storage.sst import TIER_COLD, TIER_HOT, write_sst

WINDOW = 1_000_000


def make_region(tmp_path, *, rid=1, trigger=3, window_ms=WINDOW,
                merge_mode="last_row", ttl_ms=None, append=False,
                store=None, cold_store=None, opts=None):
    meta = RegionMetadata(
        region_id=rid, table="t", tag_names=["h"], field_names=["v"],
        ts_name="ts",
        options=RegionOptions(
            compaction_trigger_files=trigger,
            compaction_window_ms=window_ms, merge_mode=merge_mode,
            ttl_ms=ttl_ms, append_mode=append,
        ),
    )
    store = store or FsObjectStore(str(tmp_path / f"data{rid}"))
    r = Region(meta, store, str(tmp_path / f"wal{rid}"),
               cold_store=cold_store)
    if opts is not None:
        r._compaction_opts = opts
    return r


def write_flush(r, hosts, ts, vals, *, op=OP_PUT):
    tags = {"h": np.asarray(hosts, object)}
    ts = np.asarray(ts, np.int64)
    if op == OP_DELETE:
        r.delete(tags, ts)
    else:
        r.write(tags, ts, {"v": np.asarray(vals, np.float64)})
    r.flush()


def levels(r):
    return sorted(m.level for m in r.manifest.state.ssts)


# ----------------------------------------------------------------------
# leveled picker
# ----------------------------------------------------------------------

def test_l0_merges_to_l1_then_l1s_to_l2(tmp_path):
    opts = CompactionOptions(l1_trigger_files=2)
    r = make_region(tmp_path, trigger=2, opts=opts)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    assert compact_once(r, opts)
    assert levels(r) == [1]
    write_flush(r, ["a"], [300], [3.0])
    write_flush(r, ["a"], [400], [4.0])
    # L0 pair merges to a second L1, then the L1 pair cascades to L2
    # inside the same compact_once call
    assert compact_once(r, opts)
    assert levels(r) == [2]
    res = r.scan()
    assert res.rows.ts.tolist() == [100, 200, 300, 400]
    r.close()


def test_l1_byte_trigger(tmp_path):
    # file-count trigger out of reach: only the byte trigger can
    # promote the accumulated L1 pair
    opts = CompactionOptions(l1_trigger_files=100, l1_trigger_bytes=1)
    r = make_region(tmp_path, trigger=2, opts=opts)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    assert compact_once(r, opts)
    write_flush(r, ["a"], [300], [3.0])
    write_flush(r, ["a"], [400], [4.0])
    assert compact_once(r, opts)
    assert levels(r) == [2]
    assert r.scan().num_rows == 4
    r.close()


def test_l2_self_merge_keeps_top_level_single(tmp_path):
    opts = CompactionOptions(l2_trigger_files=2)
    r = make_region(tmp_path, trigger=10, opts=opts)
    # install two L2 files directly (the shape left by two promoted
    # windows whose outputs later fell into one re-bucketed window)
    for i in range(2):
        rows = ColumnarRows(
            sid=np.asarray([0], np.int32),
            ts=np.asarray([100 + i], np.int64),
            seq=np.asarray([i + 1], np.uint64),
            op=np.asarray([OP_PUT], np.uint8),
            fields={"v": np.asarray([float(i)])},
        )
        m = write_sst(r.store, f"{r.prefix}/sst/l2_{i}.parquet",
                      f"l2_{i}", rows, level=2)
        with r._lock:
            r.manifest.commit({"kind": "compact", "remove_files": [],
                               "add_ssts": [m.to_json()]})
    assert compact_once(r, opts)
    assert levels(r) == [2]
    assert len(r.manifest.state.ssts) == 1
    assert r.scan().num_rows == 2
    r.close()


def test_pick_compaction_back_compat(tmp_path):
    r = make_region(tmp_path, trigger=2)
    assert pick_compaction(r) is None
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    files = pick_compaction(r)
    assert files is not None and len(files) == 2
    r.close()


def test_force_merges_untriggered_window(tmp_path):
    r = make_region(tmp_path, trigger=10)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    assert not compact_once(r)             # below trigger
    assert compact_once(r, force=True)     # ADMIN semantics
    assert len(r.manifest.state.ssts) == 1
    assert r.manifest.state.ssts[0].level == 2
    assert not compact_once(r, force=True)  # single file: no-op
    r.close()


def test_read_amplification(tmp_path):
    r = make_region(tmp_path, trigger=10)
    assert read_amplification(r) == 0
    for i in range(3):
        write_flush(r, ["a"], [100 + i], [1.0])
    # a second window with one file
    write_flush(r, ["a"], [WINDOW + 100], [1.0])
    assert read_amplification(r) == 3
    assert compact_once(r, force=True)
    assert read_amplification(r) == 1
    r.close()


# ----------------------------------------------------------------------
# tombstone GC semantics
# ----------------------------------------------------------------------

def test_tombstone_gc_on_covering_merge(tmp_path):
    r = make_region(tmp_path, trigger=2)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [100], None, op=OP_DELETE)
    tasks = pick_tasks(r, CompactionOptions())
    assert len(tasks) == 1 and tasks[0].drop_deletes
    assert compact_once(r)
    # put + covering delete annihilate: no output file at all
    assert r.manifest.state.ssts == []
    assert r.scan().num_rows == 0
    r.close()


def test_tombstone_kept_when_shadow_target_outside_merge_set(tmp_path):
    r = make_region(tmp_path, trigger=3)
    # the shadowed put lives in an L1 file
    for i in range(3):
        write_flush(r, ["a"], [100], [float(i)])
    assert compact_once(r)
    assert levels(r) == [1]
    # delete + fillers trigger an L0-only merge that does NOT cover
    # the L1 file's range
    write_flush(r, ["a"], [100], None, op=OP_DELETE)
    write_flush(r, ["a"], [200], [9.0])
    write_flush(r, ["a"], [201], [9.0])
    tasks = pick_tasks(r, CompactionOptions())
    assert tasks and tasks[0].kind == "l0" and not tasks[0].drop_deletes
    assert compact_once(r)
    # tombstone survived the merge and still shadows the L1 row
    merged = [m for m in r.manifest.state.ssts if m.level == 1
              and m.rows > 1]
    assert merged
    assert 100 not in r.scan().rows.ts.tolist()
    # a forced covering merge NOW drops the tombstone and the shadowed
    # row together — and the delete stays invisible afterwards
    assert compact_once(r, force=True)
    assert len(r.manifest.state.ssts) == 1
    res = r.scan()
    assert res.rows.ts.tolist() == [200, 201]
    assert not (r.manifest.state.ssts[0].rows > 2)
    r.close()


# ----------------------------------------------------------------------
# device merge parity
# ----------------------------------------------------------------------

def _random_rows(n=4000, seed=0, with_valid=True):
    rng = np.random.default_rng(seed)
    sid = rng.integers(0, 40, n).astype(np.int32)
    ts = rng.integers(1_700_000_000_000, 1_700_000_050_000, n)
    seq = np.arange(n, dtype=np.uint64)
    rng.shuffle(seq)
    op = np.where(rng.random(n) < 0.15, OP_DELETE, OP_PUT)
    f1 = rng.standard_normal(n)
    f1[rng.random(n) < 0.02] = np.nan
    valid = {"a": rng.random(n) < 0.6,
             "b": rng.random(n) < 0.95} if with_valid else None
    return ColumnarRows(
        sid=sid, ts=ts.astype(np.int64), seq=seq,
        op=op.astype(np.uint8),
        fields={"a": f1, "b": rng.standard_normal(n)},
        field_valid=valid,
    )


@pytest.mark.parametrize("merge_mode", ["last_row", "last_non_null"])
@pytest.mark.parametrize("drop_deletes", [False, True])
def test_device_merge_bit_identical(merge_mode, drop_deletes):
    rows = _random_rows()
    dev, path = merge_rows(rows, merge_mode=merge_mode,
                           drop_deletes=drop_deletes,
                           device_min_rows=1, verify=True)
    assert path == "device"
    host = host_merge(rows, merge_mode=merge_mode,
                      drop_deletes=drop_deletes)
    assert len(dev) == len(host)
    for name in ("sid", "ts", "seq", "op"):
        assert np.array_equal(getattr(dev, name), getattr(host, name))
    for name in dev.fields:
        assert np.array_equal(dev.fields[name], host.fields[name],
                              equal_nan=True)
    if host.field_valid is not None:
        for name in host.field_valid:
            assert np.array_equal(dev.field_valid[name],
                                  host.field_valid[name])


def test_device_merge_host_fallback_threshold():
    rows = _random_rows(n=100, with_valid=False)
    _out, path = merge_rows(rows, device_min_rows=10_000)
    assert path == "host"
    _out, path = merge_rows(rows, device_min_rows=0)
    assert path == "host"


def test_compaction_uses_device_merge_with_verification(tmp_path):
    from greptimedb_tpu.telemetry.metrics import global_registry

    opts = CompactionOptions(device_merge_min_rows=1,
                             verify_device_merge=True)
    r = make_region(tmp_path, trigger=2, opts=opts)
    write_flush(r, ["a", "b"], [100, 101], [1.0, 2.0])
    write_flush(r, ["a"], [100], [3.0])  # overwrite
    before = global_registry.get(
        "gtpu_compaction_merge_total"
    ).labels("device").value
    assert compact_once(r, opts)
    after = global_registry.get(
        "gtpu_compaction_merge_total"
    ).labels("device").value
    assert after == before + 1
    res = r.scan()
    assert res.rows.ts.tolist() == [100, 101]
    assert res.rows.fields["v"].tolist() == [3.0, 2.0]
    r.close()


# ----------------------------------------------------------------------
# races: concurrent write / truncate / TTL
# ----------------------------------------------------------------------

class _GatedStore(FsObjectStore):
    """Blocks the first compaction read until released, widening the
    race window between pick and commit."""

    def __init__(self, root):
        super().__init__(root)
        self.reading = threading.Event()
        self.release = threading.Event()
        self._armed = True

    def read_range(self, path, offset, length):
        if self._armed and "/sst/" in path:
            self._armed = False
            self.reading.set()
            assert self.release.wait(10)
        return super().read_range(path, offset, length)


def test_concurrent_write_during_compaction(tmp_path):
    store = _GatedStore(str(tmp_path / "data"))
    r = make_region(tmp_path, trigger=2, store=store)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    t = threading.Thread(target=compact_once, args=(r,))
    t.start()
    assert store.reading.wait(10)
    # a write + flush lands while the merge is mid-read
    write_flush(r, ["b"], [300], [3.0])
    store.release.set()
    t.join(10)
    assert not t.is_alive()
    res = r.scan()
    assert res.rows.ts.tolist() == [100, 200, 300]
    # merged output + the concurrently flushed file
    assert len(r.manifest.state.ssts) == 2
    r.close()


def test_truncate_during_compaction_aborts_cleanly(tmp_path):
    store = _GatedStore(str(tmp_path / "data"))
    r = make_region(tmp_path, trigger=2, store=store)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    result = {}
    t = threading.Thread(
        target=lambda: result.setdefault("did", compact_once(r))
    )
    t.start()
    assert store.reading.wait(10)
    r.truncate()
    store.release.set()
    t.join(10)
    assert result["did"] is False
    assert r.scan().num_rows == 0
    # the aborted merge's output was deleted, truncation left nothing
    assert store.list(r.prefix + "/sst/") == []
    r.close()


def test_ttl_purge_during_compaction_aborts_cleanly(tmp_path):
    store = _GatedStore(str(tmp_path / "data"))
    r = make_region(tmp_path, trigger=2, store=store, ttl_ms=1000)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    result = {}
    t = threading.Thread(
        target=lambda: result.setdefault("did", compact_once(r))
    )
    t.start()
    assert store.reading.wait(10)
    # TTL expiry removes both picked inputs mid-merge
    assert purge_expired(r, now_ms=10_000_000) == 2
    store.release.set()
    t.join(10)
    assert result["did"] is False
    assert r.manifest.state.ssts == []
    assert store.list(r.prefix + "/sst/") == []
    r.close()


def test_purge_expired_is_tier_aware(tmp_path):
    cold = MemoryObjectStore()
    opts = CompactionOptions(cold_horizon_ms=1)
    r = make_region(tmp_path, trigger=10, ttl_ms=1000,
                    cold_store=cold, opts=opts)
    write_flush(r, ["a"], [100], [1.0])
    # rewrite the quiesced window onto the cold tier
    assert compact_once(r, opts, now_ms=10 * WINDOW)
    m = r.manifest.state.ssts[0]
    assert m.tier == TIER_COLD
    assert cold.exists(m.path)
    assert purge_expired(r, now_ms=10_000_000) == 1
    assert not cold.exists(m.path)
    assert r.manifest.state.ssts == []
    r.close()


# ----------------------------------------------------------------------
# hot/cold tiering
# ----------------------------------------------------------------------

def test_tiering_rewrites_old_window_cold(tmp_path):
    cold = MemoryObjectStore()
    opts = CompactionOptions(cold_horizon_ms=5 * WINDOW)
    r = make_region(tmp_path, trigger=10, cold_store=cold, opts=opts)
    write_flush(r, ["a", "b"], [100, 200], [1.0, 2.0])   # old window
    now = 100 * WINDOW
    write_flush(r, ["a"], [now - 10], [3.0])             # recent window
    tasks = pick_tasks(r, opts, now_ms=now)
    assert [t.kind for t in tasks] == ["tier"]
    assert compact_once(r, opts, now_ms=now)
    tiers = {m.tier for m in r.manifest.state.ssts}
    assert tiers == {TIER_COLD, TIER_HOT}
    cold_meta = [m for m in r.manifest.state.ssts
                 if m.tier == TIER_COLD][0]
    assert cold_meta.level == 2
    assert "/cold/" in cold_meta.path
    assert cold.exists(cold_meta.path)
    # scans read through the cold store transparently (rows come back
    # (sid, ts)-sorted, so compare as sets)
    res = r.scan()
    assert sorted(res.rows.ts.tolist()) == [100, 200, now - 10]
    # the cold window does not re-pick (already cold, single file)
    assert pick_tasks(r, opts, now_ms=now) == []
    r.close()


def test_tier_survives_reopen_and_restore_skips_cold_warm(tmp_path):
    from greptimedb_tpu.storage.page_cache import global_page_cache
    from greptimedb_tpu.storage.recovery import restore_region_ssts

    cold = MemoryObjectStore()
    opts = CompactionOptions(cold_horizon_ms=1)
    store = FsObjectStore(str(tmp_path / "data1"))
    r = make_region(tmp_path, trigger=10, cold_store=cold, opts=opts,
                    store=store)
    write_flush(r, ["a"], [100], [1.0])
    assert compact_once(r, opts, now_ms=10 * WINDOW)
    r.close()
    r2 = Region(r.meta, store, str(tmp_path / "wal1"), cold_store=cold)
    assert r2.manifest.state.ssts[0].tier == TIER_COLD
    stats = restore_region_ssts(r2, prefetch_depth=2)
    # cold files fetch + verify but never warm the page cache
    assert stats["files"] == 1
    assert stats["installed_cols"] == 0
    assert not any(
        key[0] == r2.manifest.state.ssts[0].path
        for key in global_page_cache._entries
    )
    assert r2.scan().num_rows == 1
    r2.close()


# ----------------------------------------------------------------------
# orphan cleanup at open
# ----------------------------------------------------------------------

def test_orphan_sst_cleanup_on_reopen(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path), enable_background=False)
    eng = TsdbEngine(cfg)
    meta = RegionMetadata(region_id=7, table="t", tag_names=["h"],
                          field_names=["v"], ts_name="ts")
    r = eng.create_region(meta)
    r.write({"h": np.asarray(["a"], object)},
            np.asarray([100], np.int64), {"v": np.asarray([1.0])})
    r.flush()
    live = r.manifest.state.ssts[0].path
    # a crash between SST write and manifest commit leaves orphans
    eng.store.write(f"{r.prefix}/sst/deadbeef.parquet", b"orphan")
    eng.store.write(f"{r.prefix}/cold/deadcold.parquet", b"orphan")
    eng.close()
    eng2 = TsdbEngine(cfg)
    r2 = eng2.open_region(meta)
    paths = {m.path for m in eng2.store.list(r2.prefix + "/sst/")}
    assert paths == {live}
    assert eng2.store.list(r2.prefix + "/cold/") == []
    assert r2.scan().num_rows == 1
    eng2.close()


def test_cleanup_orphans_respects_live_set(tmp_path):
    r = make_region(tmp_path, trigger=10)
    write_flush(r, ["a"], [100], [1.0])
    assert cleanup_orphan_ssts(r) == 0
    r.store.write(f"{r.prefix}/sst/zzzz.parquet", b"x")
    assert cleanup_orphan_ssts(r) == 1
    assert r.scan().num_rows == 1
    r.close()


# ----------------------------------------------------------------------
# scheduler: pool, dedupe, maintenance isolation
# ----------------------------------------------------------------------

def test_scheduler_dedupes_inflight_region(tmp_path):
    store = _GatedStore(str(tmp_path / "data"))
    r = make_region(tmp_path, trigger=2, store=store)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    sched = CompactionScheduler(CompactionOptions(workers=2))
    try:
        fut = sched.schedule(r)
        assert fut is not None
        assert store.reading.wait(10)
        assert sched.schedule(r) is None     # deduped while in flight
        store.release.set()
        assert fut.result(timeout=10) is True
        assert sched.maybe_schedule(r) is False  # nothing triggered
    finally:
        sched.close()
    r.close()


def test_one_bad_window_does_not_starve_others(tmp_path):
    """A deterministically failing input in one window must not abort
    the region's OTHER windows' merges (they would otherwise
    accumulate files forever); the first error still surfaces typed
    after every window got its attempt."""
    from greptimedb_tpu.errors import SstRestoreError
    from greptimedb_tpu.telemetry.metrics import global_registry

    r = make_region(tmp_path, trigger=2)
    # window 0: two good files; window 1: one file corrupted on disk
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    write_flush(r, ["a"], [WINDOW + 100], [3.0])
    write_flush(r, ["a"], [WINDOW + 200], [4.0])
    bad = [m for m in r.manifest.state.ssts
           if m.ts_max > WINDOW][0]
    r.store.write(bad.path, b"truncated")   # short vs manifest bytes
    errs0 = global_registry.get(
        "gtpu_compaction_errors_total"
    ).labels().value
    with pytest.raises(SstRestoreError):
        compact_once(r)
    # the good window merged despite the bad one
    good = [m for m in r.manifest.state.ssts if m.ts_max <= WINDOW]
    assert len(good) == 1 and good[0].level == 1
    assert global_registry.get(
        "gtpu_compaction_errors_total"
    ).labels().value == errs0 + 1
    r.close()


def test_compact_sync_after_close_is_typed(tmp_path):
    from greptimedb_tpu.errors import CompactionError

    r = make_region(tmp_path, trigger=2)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [200], [2.0])
    sched = CompactionScheduler(CompactionOptions())
    sched.close()
    with pytest.raises(CompactionError):
        sched.compact_sync(r, force=True)
    # idle region with nothing picked short-circuits without the pool
    r2 = make_region(tmp_path, rid=2, trigger=2)
    sched2 = CompactionScheduler(CompactionOptions())
    try:
        assert sched2.compact_sync(r2) is False
    finally:
        sched2.close()
    r.close()
    r2.close()


def test_engine_maintenance_error_isolation(tmp_path, monkeypatch):
    """One region's failing purge/compact must not abort the other
    regions' maintenance for the tick (the old loop-level try/except
    did exactly that)."""
    cfg = EngineConfig(data_root=str(tmp_path), enable_background=False)
    cfg.compaction.workers = 1
    eng = TsdbEngine(cfg)
    metas = [
        RegionMetadata(region_id=i, table=f"t{i}", tag_names=["h"],
                       field_names=["v"], ts_name="ts",
                       options=RegionOptions(compaction_trigger_files=2))
        for i in (1, 2)
    ]
    r1, r2 = (eng.create_region(m) for m in metas)
    for r in (r1, r2):
        write_flush(r, ["a"], [100], [1.0])
        write_flush(r, ["a"], [200], [2.0])
    import greptimedb_tpu.storage.compaction as comp

    real_purge = comp.purge_expired

    def flaky_purge(region, **kw):
        if region.meta.region_id == 1:
            raise RuntimeError("boom")
        return real_purge(region, **kw)

    monkeypatch.setattr(comp, "purge_expired", flaky_purge)
    eng.run_maintenance()   # must not raise
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(r2.manifest.state.ssts) == 1:
            break
        time.sleep(0.05)
    # region 2's compaction ran despite region 1's failing purge
    assert len(r2.manifest.state.ssts) == 1
    assert len(r1.manifest.state.ssts) == 2
    eng.close()


def test_engine_wires_scheduler_and_read_amp_gauge(tmp_path):
    from greptimedb_tpu.telemetry.metrics import global_registry

    cfg = EngineConfig(data_root=str(tmp_path), enable_background=False)
    eng = TsdbEngine(cfg)
    meta = RegionMetadata(
        region_id=3, table="t", tag_names=["h"], field_names=["v"],
        ts_name="ts",
        options=RegionOptions(compaction_trigger_files=10),
    )
    r = eng.create_region(meta)
    assert r._compaction is eng.compaction
    for i in range(3):
        write_flush(r, ["a"], [100 + i], [1.0])
    assert eng.compaction.update_read_amp([r]) == 3
    assert r.compact(force=True)            # routes through the pool
    assert eng.compaction.update_read_amp([r]) == 1
    rendered = global_registry.render()
    assert "gtpu_compaction_read_amp" in rendered
    assert "gtpu_compaction_total" in rendered
    assert "gtpu_compaction_stage_ms_total" in rendered
    assert 'gtpu_compaction_bytes_total{direction="in"}' in rendered
    eng.close()


# ----------------------------------------------------------------------
# ADMIN surface + cache invalidation (full statement path)
# ----------------------------------------------------------------------

@pytest.fixture()
def inst(tmp_path):
    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


def _fill(inst, n_flushes=3):
    inst.execute_sql(
        "create table cpu (ts timestamp time index, "
        "host string primary key, usage double)"
    )
    table = inst.catalog.table("public", "cpu")
    for i in range(n_flushes):
        table.write(
            {"host": np.asarray(["a", "b"], object)},
            np.asarray([1000 + i, 2000 + i], np.int64),
            {"usage": np.asarray([1.0 + i, 2.0 + i])},
        )
        table.flush()
    return table


def test_admin_compact_table_routes_through_pool(inst):
    table = _fill(inst)
    region = table.regions[0]
    assert len(region.manifest.state.ssts) == 3
    r = inst.sql("ADMIN compact_table('cpu')")
    assert r.cols[0].values[0] == 1
    assert len(region.manifest.state.ssts) == 1
    assert region.manifest.state.ssts[0].level == 2
    # count survives the merge
    res = inst.sql("select count(usage) from cpu")
    assert res.cols[0].values[0] == 6
    # idempotent second pass
    r = inst.sql("ADMIN compact_table('cpu')")
    assert r.cols[0].values[0] == 0


def test_admin_flush_and_compact_typed_errors(inst):
    with pytest.raises(TableNotFoundError):
        inst.sql("ADMIN compact_table('nope')")
    with pytest.raises(TableNotFoundError):
        inst.sql("ADMIN flush_table('nope')")


def test_compaction_metrics_in_runtime_metrics(inst):
    _fill(inst)
    inst.sql("ADMIN compact_table('cpu')")
    res = inst.sql(
        "select metric_name from information_schema.runtime_metrics"
    )
    names = set(res.cols[0].values)
    assert "gtpu_compaction_total" in names
    assert "gtpu_compaction_stage_ms_total" in names
    assert "gtpu_compaction_read_amp" in names


def test_caches_invalidate_across_gc_compaction(inst, tmp_path):
    """Result cache + merged-scan state must never serve rows a
    tombstone-GC compaction removed: physical_version bumps on the
    compact commit, and the delete itself bumps the logical version."""
    from greptimedb_tpu.query.result_cache import ResultCache

    inst.result_cache = ResultCache(enabled=True, max_bytes=1 << 20)
    inst.catalog.result_cache = inst.result_cache
    table = _fill(inst, n_flushes=2)
    q = "select count(usage) from cpu"
    assert inst.sql(q).cols[0].values[0] == 4
    assert inst.sql(q).cols[0].values[0] == 4      # cached poll
    # delete one key, flush, GC-compact everything
    table.regions[0].delete(
        {"host": np.asarray(["a", "a"], object)},
        np.asarray([1000, 1001], np.int64),
    )
    table.flush()
    v_before = table.physical_version()
    inst.sql("ADMIN compact_table('cpu')")
    assert table.physical_version() != v_before
    assert inst.sql(q).cols[0].values[0] == 2
    # tombstones were dropped by the covering merge, result stays right
    region = table.regions[0]
    assert all((m.level, m.rows) == (2, 2)
               for m in region.manifest.state.ssts)


def test_twcs_trigger_table_option(inst):
    """`compaction.twcs.trigger_file_num` (reference twcs knob) sets
    the per-table L0 trigger through CREATE ... WITH(...)."""
    inst.execute_sql(
        "create table opt (ts timestamp time index, v double) "
        "with ('compaction.twcs.trigger_file_num' = '2')"
    )
    table = inst.catalog.table("public", "opt")
    region = table.regions[0]
    assert region.meta.options.compaction_trigger_files == 2
    for i in range(2):
        table.write({}, np.asarray([1000 + i], np.int64),
                    {"v": np.asarray([float(i)])})
        table.flush()
    # two L0 files satisfy the table's trigger without force
    assert region.compact()
    assert len(region.manifest.state.ssts) == 1


def test_append_mode_compaction_keeps_all_rows(tmp_path):
    r = make_region(tmp_path, trigger=2, append=True)
    write_flush(r, ["a"], [100], [1.0])
    write_flush(r, ["a"], [100], [2.0])   # duplicate key, append mode
    assert compact_once(r)
    assert len(r.manifest.state.ssts) == 1
    res = r.scan()
    assert res.num_rows == 2
    r.close()
