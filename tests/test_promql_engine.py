"""PromQL engine tests: parser + evaluator over the standalone instance,
validated against hand-computed Prometheus semantics (the golden-case role
of /root/reference/tests/cases/standalone/common/tql/)."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.promql.engine import PromEngine, ScalarValue, VectorValue
from greptimedb_tpu.promql.parser import (
    Agg,
    Binary,
    Call,
    NumberLit,
    VectorSelector,
    parse_promql,
    parse_duration_ms,
)


# ----------------------------------------------------------------------
# parser
# ----------------------------------------------------------------------

def test_parse_duration():
    assert parse_duration_ms("5m") == 300_000
    assert parse_duration_ms("1h30m") == 5_400_000
    assert parse_duration_ms("250ms") == 250
    assert parse_duration_ms("2d") == 172_800_000


def test_parse_selector():
    e = parse_promql('http_requests{job="api", code=~"5.."}[5m]')
    assert isinstance(e, VectorSelector)
    assert e.name == "http_requests"
    assert e.range_ms == 300_000
    assert [(m.name, m.op, m.value) for m in e.matchers] == [
        ("job", "=", "api"), ("code", "=~", "5.."),
    ]


def test_parse_rate_and_agg():
    e = parse_promql('sum by (host) (rate(cpu_seconds[1m]))')
    assert isinstance(e, Agg)
    assert e.op == "sum" and e.grouping == ["host"] and not e.without
    assert isinstance(e.expr, Call) and e.expr.name == "rate"


def test_parse_binary_precedence():
    e = parse_promql("a + b * c")
    assert isinstance(e, Binary) and e.op == "+"
    assert isinstance(e.rhs, Binary) and e.rhs.op == "*"


def test_parse_offset_and_bool():
    e = parse_promql("foo offset 5m > bool 2")
    assert isinstance(e, Binary) and e.bool_mod
    assert e.lhs.offset_ms == 300_000


def test_parse_on_group_left():
    e = parse_promql("a * on(host) group_left(extra) b")
    assert e.matching.on and e.matching.labels == ["host"]
    assert e.matching.group == "left"
    assert e.matching.include == ["extra"]


# ----------------------------------------------------------------------
# engine fixtures
# ----------------------------------------------------------------------

@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


T0 = 1_700_000_000_000  # aligned base


def setup_counter(inst):
    """Counter series: host h1 increases 10/s, h2 increases 20/s, 15s
    samples over 10 minutes."""
    inst.sql(
        "CREATE TABLE http_requests (host STRING, job STRING, "
        "greptime_value DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host, job))"
    )
    table = inst.catalog.table("public", "http_requests")
    n = 41  # 10 min / 15s + 1
    ts = T0 + np.arange(n) * 15_000
    for host, rate in (("h1", 10.0), ("h2", 20.0)):
        table.write(
            {"host": np.full(n, host, object),
             "job": np.full(n, "api", object)},
            ts,
            {"greptime_value": np.arange(n) * 15.0 * rate},
        )
    return ts


def setup_gauge(inst):
    inst.sql(
        "CREATE TABLE mem_used (host STRING, greptime_value DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "mem_used")
    n = 21
    ts = T0 + np.arange(n) * 30_000
    table.write(
        {"host": np.full(n, "h1", object)}, ts,
        {"greptime_value": 100.0 + 10.0 * np.sin(np.arange(n))},
    )
    table.write(
        {"host": np.full(n, "h2", object)}, ts,
        {"greptime_value": np.full(n, 50.0)},
    )
    return ts


def q(inst, promql, start, end, step):
    eng = PromEngine(inst)
    val, ev = eng.query_range(promql, start, end, step)
    return val, ev


# ----------------------------------------------------------------------
# engine: selectors and range functions
# ----------------------------------------------------------------------

def test_instant_selector_lookback(inst):
    setup_gauge(inst)
    val, ev = q(inst, "mem_used", T0 + 60_000, T0 + 120_000, 30_000)
    assert isinstance(val, VectorValue)
    assert val.num_series == 2
    assert val.present.all()
    h2 = [i for i, l in enumerate(val.labels) if l.get("host") == "h2"][0]
    np.testing.assert_allclose(val.values[h2], 50.0)


def test_selector_matcher_filters(inst):
    setup_gauge(inst)
    val, _ = q(inst, 'mem_used{host="h2"}', T0 + 60_000, T0 + 60_000, 1000)
    assert val.num_series == 1
    assert val.labels[0]["host"] == "h2"


def test_rate_counter(inst):
    setup_counter(inst)
    val, _ = q(
        inst, "rate(http_requests[1m])", T0 + 120_000, T0 + 300_000, 60_000
    )
    assert val.num_series == 2
    for i, lab in enumerate(val.labels):
        want = 10.0 if lab["host"] == "h1" else 20.0
        assert val.present[i].all()
        np.testing.assert_allclose(val.values[i], want, rtol=1e-5)


def test_increase(inst):
    setup_counter(inst)
    val, _ = q(
        inst, "increase(http_requests[2m])", T0 + 180_000, T0 + 300_000,
        60_000,
    )
    for i, lab in enumerate(val.labels):
        want = (1200.0 if lab["host"] == "h1" else 2400.0)
        np.testing.assert_allclose(val.values[i], want, rtol=1e-5)


def test_avg_over_time(inst):
    setup_gauge(inst)
    val, _ = q(
        inst, "avg_over_time(mem_used[2m])", T0 + 300_000, T0 + 300_000, 1000
    )
    h2 = [i for i, l in enumerate(val.labels) if l.get("host") == "h2"][0]
    np.testing.assert_allclose(val.values[h2], 50.0)
    h1 = 1 - h2
    # window (180s, 300s]: samples at 210,240,270,300s -> sin(7..10)
    want = np.mean(100.0 + 10.0 * np.sin(np.arange(7, 11)))
    np.testing.assert_allclose(val.values[h1], want, rtol=1e-5)


def test_min_max_over_time(inst):
    setup_gauge(inst)
    vmin, _ = q(inst, "min_over_time(mem_used[5m])",
                T0 + 300_000, T0 + 300_000, 1000)
    vmax, _ = q(inst, "max_over_time(mem_used[5m])",
                T0 + 300_000, T0 + 300_000, 1000)
    h1min = [i for i, l in enumerate(vmin.labels) if l["host"] == "h1"][0]
    h1max = [i for i, l in enumerate(vmax.labels) if l["host"] == "h1"][0]
    xs = 100.0 + 10.0 * np.sin(np.arange(1, 11))
    np.testing.assert_allclose(vmin.values[h1min], xs.min(), rtol=1e-6)
    np.testing.assert_allclose(vmax.values[h1max], xs.max(), rtol=1e-6)


def test_delta_gauge(inst):
    setup_gauge(inst)
    val, _ = q(inst, "delta(mem_used[2m])", T0 + 300_000, T0 + 300_000, 1000)
    h2 = [i for i, l in enumerate(val.labels) if l["host"] == "h2"][0]
    np.testing.assert_allclose(val.values[h2], 0.0, atol=1e-6)


def test_changes_resets(inst):
    inst.sql(
        "CREATE TABLE flip (greptime_value DOUBLE, ts TIMESTAMP TIME INDEX)"
    )
    t = inst.catalog.table("public", "flip")
    ts = T0 + np.arange(10) * 1000
    vals = np.asarray([1.0, 1.0, 2.0, 1.0, 1.0, 3.0, 3.0, 0.0, 0.0, 5.0])
    t.write({}, ts, {"greptime_value": vals})
    val, _ = q(inst, "changes(flip[10s])", T0 + 9_000, T0 + 9_000, 1000)
    # pairs fully inside window: changes at 2,1,3,0,5 transitions = 5
    assert val.values[0][0] == 5.0
    val, _ = q(inst, "resets(flip[10s])", T0 + 9_000, T0 + 9_000, 1000)
    assert val.values[0][0] == 2.0  # 2->1 and 3->0


# ----------------------------------------------------------------------
# engine: aggregation
# ----------------------------------------------------------------------

def test_sum_aggregation(inst):
    setup_gauge(inst)
    val, _ = q(inst, "sum(mem_used)", T0 + 60_000, T0 + 120_000, 30_000)
    assert val.num_series == 1 and val.labels[0] == {}
    h1_vals = 100.0 + 10.0 * np.sin(np.arange(2, 5))
    np.testing.assert_allclose(val.values[0], h1_vals + 50.0, rtol=1e-5)


def test_sum_by(inst):
    setup_counter(inst)
    val, _ = q(
        inst, "sum by (host) (rate(http_requests[1m]))",
        T0 + 120_000, T0 + 120_000, 1000,
    )
    assert val.num_series == 2
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    np.testing.assert_allclose(by_host["h1"], 10.0, rtol=1e-5)
    np.testing.assert_allclose(by_host["h2"], 20.0, rtol=1e-5)


def test_avg_without(inst):
    setup_gauge(inst)
    val, _ = q(
        inst, "avg without (host) (mem_used)",
        T0 + 120_000, T0 + 120_000, 1000,
    )
    assert val.num_series == 1
    want = (100.0 + 10.0 * np.sin(4) + 50.0) / 2
    np.testing.assert_allclose(val.values[0][0], want, rtol=1e-6)


def test_topk(inst):
    setup_gauge(inst)
    val, _ = q(inst, "topk(1, mem_used)", T0 + 120_000, T0 + 120_000, 1000)
    assert val.num_series == 1
    assert val.labels[0]["host"] == "h1"  # 100+10sin(4) ≈ 92.4 > 50


def test_quantile_agg(inst):
    setup_gauge(inst)
    val, _ = q(
        inst, "quantile(0.5, mem_used)", T0 + 120_000, T0 + 120_000, 1000
    )
    h1 = 100.0 + 10.0 * np.sin(4)
    want = (h1 + 50.0) / 2  # median of two = midpoint
    np.testing.assert_allclose(val.values[0][0], want, rtol=1e-6)


def test_count_and_group(inst):
    setup_gauge(inst)
    val, _ = q(inst, "count(mem_used)", T0 + 120_000, T0 + 120_000, 1000)
    assert val.values[0][0] == 2.0


# ----------------------------------------------------------------------
# engine: binary operators
# ----------------------------------------------------------------------

def test_vector_scalar_arith(inst):
    setup_gauge(inst)
    val, _ = q(inst, "mem_used / 2", T0 + 120_000, T0 + 120_000, 1000)
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    np.testing.assert_allclose(by_host["h2"], 25.0)


def test_vector_scalar_filter(inst):
    setup_gauge(inst)
    val, _ = q(inst, "mem_used > 60", T0 + 120_000, T0 + 120_000, 1000)
    present_hosts = [
        val.labels[i]["host"] for i in range(val.num_series)
        if val.present[i][0]
    ]
    assert present_hosts == ["h1"]


def test_vector_scalar_bool(inst):
    setup_gauge(inst)
    val, _ = q(inst, "mem_used > bool 60", T0 + 120_000, T0 + 120_000, 1000)
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    assert by_host == {"h1": 1.0, "h2": 0.0}


def test_vector_vector_matching(inst):
    setup_gauge(inst)
    val, _ = q(inst, "mem_used + mem_used", T0 + 120_000, T0 + 120_000, 1000)
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    np.testing.assert_allclose(by_host["h2"], 100.0)


def test_scalar_scalar(inst):
    val, _ = q(inst, "2 + 3 * 4", T0, T0, 1000)
    assert isinstance(val, ScalarValue)
    assert val.values[0] == 14.0


def test_set_ops(inst):
    setup_gauge(inst)
    val, _ = q(
        inst, 'mem_used and mem_used{host="h1"}',
        T0 + 120_000, T0 + 120_000, 1000,
    )
    assert [l["host"] for l in val.labels
            if val.present[val.labels.index(l)][0]] == ["h1"]
    val, _ = q(
        inst, 'mem_used unless mem_used{host="h1"}',
        T0 + 120_000, T0 + 120_000, 1000,
    )
    present = [val.labels[i]["host"] for i in range(val.num_series)
               if val.present[i][0]]
    assert present == ["h2"]


# ----------------------------------------------------------------------
# engine: functions
# ----------------------------------------------------------------------

def test_math_function(inst):
    setup_gauge(inst)
    val, _ = q(inst, "abs(mem_used - 100)", T0 + 120_000, T0 + 120_000, 1000)
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    np.testing.assert_allclose(by_host["h2"], 50.0)


def test_histogram_quantile(inst):
    inst.sql(
        "CREATE TABLE latency_bucket (le STRING, greptime_value DOUBLE, "
        "ts TIMESTAMP TIME INDEX, PRIMARY KEY (le))"
    )
    t = inst.catalog.table("public", "latency_bucket")
    ts = np.asarray([T0])
    # cumulative: 10 below 0.1, 60 below 0.5, 100 below 1, 100 total
    for le, c in (("0.1", 10.0), ("0.5", 60.0), ("1", 100.0),
                  ("+Inf", 100.0)):
        t.write({"le": np.asarray([le], object)}, ts,
                {"greptime_value": np.asarray([c])})
    val, _ = q(
        inst, "histogram_quantile(0.5, latency_bucket)", T0, T0, 1000
    )
    assert val.num_series == 1
    # rank 50: bucket (0.1, 0.5], interpolate (50-10)/(60-10) = 0.8
    np.testing.assert_allclose(val.values[0][0], 0.1 + 0.4 * 0.8, rtol=1e-6)


def test_absent(inst):
    setup_gauge(inst)
    val, _ = q(
        inst, 'absent(mem_used{host="nope"})', T0 + 60_000, T0 + 60_000,
        1000,
    )
    assert val.num_series == 1
    assert val.labels[0] == {"host": "nope"}
    assert val.values[0][0] == 1.0


def test_label_replace(inst):
    setup_gauge(inst)
    val, _ = q(
        inst,
        'label_replace(mem_used, "node", "$1", "host", "(h.)")',
        T0 + 60_000, T0 + 60_000, 1000,
    )
    assert all(l["node"] == l["host"] for l in val.labels)


def test_offset(inst):
    setup_gauge(inst)
    # at T0+300s, offset 2m reads the value at T0+180s
    val, _ = q(
        inst, 'mem_used{host="h1"} offset 2m', T0 + 300_000, T0 + 300_000,
        1000,
    )
    want = 100.0 + 10.0 * np.sin(6)  # sample at 180s
    np.testing.assert_allclose(val.values[0][0], want, rtol=1e-6)


def test_subquery_max_of_rate(inst):
    setup_counter(inst)
    val, _ = q(
        inst, "max_over_time(rate(http_requests[1m])[5m:1m])",
        T0 + 420_000, T0 + 420_000, 1000,
    )
    by_host = {l["host"]: val.values[i][0] for i, l in enumerate(val.labels)}
    np.testing.assert_allclose(by_host["h1"], 10.0, rtol=1e-4)
    np.testing.assert_allclose(by_host["h2"], 20.0, rtol=1e-4)


def test_tql_eval_through_sql(inst):
    setup_gauge(inst)
    res = inst.sql(
        f"TQL EVAL ({(T0 + 60_000) // 1000}, {(T0 + 120_000) // 1000}, "
        f"'30s') mem_used{{host=\"h2\"}}"
    )
    assert res.names[0] == "ts" and "value" in res.names
    assert res.num_rows == 3
    assert all(r[1] == 50.0 for r in res.rows())
