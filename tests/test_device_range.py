"""Device RANGE execution (query/device_range.py) vs the host path.

The device path runs the same RANGE plans over HBM-resident per-cell
partial-state grids (the page-cache analog of the reference's hot datanode,
/root/reference/src/query/src/range_select/plan.rs); results must agree
with the host NumPy path up to f32 accumulation.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query.executor import QueryEngine


@pytest.fixture
def inst(tmp_path):
    i = Standalone(str(tmp_path))
    yield i
    i.close()


@pytest.fixture
def cpu(inst, rng):
    inst.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " region string primary key, u double, v double)"
    )
    n_hosts, t = 16, 400
    tab = inst.catalog.table("public", "cpu")
    ts = np.tile(np.arange(t) * 1000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i}" for i in range(n_hosts)], t).astype(object)
    regions = np.repeat(
        [f"r{i % 3}" for i in range(n_hosts)], t
    ).astype(object)
    u = rng.random(n_hosts * t) * 100
    v = rng.random(n_hosts * t) * 10
    valid = rng.random(n_hosts * t) > 0.05
    tab.write({"host": hosts, "region": regions}, ts, {"u": u, "v": v},
              field_valid={"u": valid})
    return inst


QUERIES = [
    "SELECT ts, host, avg(u) RANGE '10s' FROM cpu ALIGN '10s' BY (host) "
    "ORDER BY ts, host",
    "SELECT ts, region, sum(u) RANGE '20s', max(v) RANGE '20s', "
    "min(u) RANGE '20s' FROM cpu ALIGN '10s' BY (region) "
    "ORDER BY ts, region",
    "SELECT ts, count(u) RANGE '30s', count(*) RANGE '30s' FROM cpu "
    "ALIGN '30s' BY () ORDER BY ts",
    "SELECT ts, host, last_value(u) RANGE '25s', first_value(v) RANGE '25s' "
    "FROM cpu ALIGN '5s' BY (host) ORDER BY ts, host LIMIT 400",
    "SELECT ts, host, stddev(u) RANGE '40s' FROM cpu "
    "WHERE ts >= 100000 AND ts < 300000 ALIGN '20s' BY (host) "
    "ORDER BY ts, host",
    "SELECT ts, region, avg(u) RANGE '10s' FILL PREV FROM cpu "
    "WHERE host != 'h3' ALIGN '10s' BY (region) ORDER BY ts, region",
    "SELECT ts, avg(u) RANGE '1m' FILL LINEAR FROM cpu WHERE host = 'h1' "
    "ALIGN '30s' ORDER BY ts",
    "SELECT ts, host, var_pop(u) RANGE '30s', avg(v) RANGE '30s' AS av "
    "FROM cpu ALIGN '15s' BY (host) HAVING av > 4 ORDER BY ts, host",
]


def _compare(rh, rd, q):
    assert rh.names == rd.names
    assert rh.num_rows == rd.num_rows, q
    for i in range(len(rh.names)):
        a, b = rh.cols[i], rd.cols[i]
        assert (a.valid_mask == b.valid_mask).all(), (q, rh.names[i])
        if a.values.dtype == object:
            assert (a.values == b.values).all(), (q, rh.names[i])
        else:
            m = a.valid_mask
            assert np.allclose(
                np.asarray(a.values, float)[m],
                np.asarray(b.values, float)[m],
                rtol=2e-4, atol=1e-3,
            ), (q, rh.names[i])


@pytest.mark.parametrize("q", QUERIES)
def test_device_range_matches_host(cpu, q):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device", q
    _compare(rh, rd, q)


def test_device_range_cache_hit_and_invalidation(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    q = QUERIES[0]
    r1 = inst.sql(q)
    cache = inst.query_engine.range_cache
    assert len(cache._entries) == 1
    entry = next(iter(cache._entries.values()))
    r2 = inst.sql(q)
    assert next(iter(cache._entries.values())) is entry  # reused
    assert r1.rows() == r2.rows()
    # a write bumps the data version and invalidates the entry
    inst.execute_sql(
        "insert into cpu (ts, host, region, u, v) "
        "values (400000, 'h0', 'r0', 50.0, 5.0)"
    )
    r3 = inst.sql(q)
    entry2 = next(iter(cache._entries.values()))
    assert entry2 is not entry
    assert r3.num_rows == r1.num_rows + 1


def test_device_range_falls_back_on_residual(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    # residual filter on a field value is not expressible over partials
    r = inst.sql(
        "SELECT ts, host, avg(u) RANGE '10s' FROM cpu WHERE v > 5 "
        "ALIGN '10s' BY (host) ORDER BY ts, host"
    )
    assert inst.query_engine.last_exec_path == "host"
    assert r.num_rows > 0


def test_first_last_tiebreak_matches_host(inst, rng):
    """BY coarser than series + fully aligned timestamps (typical TSBS
    shape): equal-ts ties must resolve identically on host and device
    ((ts, sid) lexicographic — ADVICE r2 medium)."""
    inst.execute_sql(
        "create table m (ts timestamp time index, host string primary key,"
        " dc string primary key, x double)"
    )
    tab = inst.catalog.table("public", "m")
    n_hosts, t = 12, 50
    ts = np.tile(np.arange(t) * 1000, n_hosts).astype(np.int64)  # aligned
    hosts = np.repeat([f"h{i:02d}" for i in range(n_hosts)], t).astype(object)
    dcs = np.repeat([f"d{i % 2}" for i in range(n_hosts)], t).astype(object)
    x = rng.random(n_hosts * t) * 100
    tab.write({"host": hosts, "dc": dcs}, ts, {"x": x})
    q = (
        "SELECT ts, dc, last_value(x) RANGE '10s', first_value(x) "
        "RANGE '10s' FROM m ALIGN '10s' BY (dc) ORDER BY ts, dc"
    )
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device"
    # exact equality at f32 (device value precision): the winning row must
    # be the same row, not merely a close value
    for i in range(len(rh.names)):
        if rh.cols[i].values.dtype != object:
            np.testing.assert_array_equal(
                np.asarray(rh.cols[i].values, np.float64).astype(np.float32),
                np.asarray(rd.cols[i].values, np.float64).astype(np.float32),
                err_msg=rh.names[i],
            )


def test_long_span_exact(inst, rng):
    """Spans beyond 2^31 ms stay exact on device: (cell, intra) int32
    pairs replace the lossy global tick (ADVICE r2 low)."""
    inst.execute_sql(
        "create table lng (ts timestamp time index, host string primary key,"
        " x double)"
    )
    tab = inst.catalog.table("public", "lng")
    # ~50 days at irregular offsets; interval gcd stays 1000ms
    base = np.arange(200, dtype=np.int64) * (25 * 3600 * 1000) + 13_000
    ts = np.concatenate([base, base + 1000])
    hosts = np.asarray(["a"] * 200 + ["b"] * 200, object)
    x = rng.random(400) * 10
    tab.write({"host": hosts}, ts, {"x": x})
    assert ts.max() - ts.min() > 2**31
    q = (
        "SELECT ts, last_value(x) RANGE '1d', max(x) RANGE '1d' FROM lng "
        "ALIGN '1d' BY () ORDER BY ts"
    )
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device"
    assert rh.num_rows == rd.num_rows
    for i in range(len(rh.names)):
        np.testing.assert_allclose(
            np.asarray(rh.cols[i].values, float),
            np.asarray(rd.cols[i].values, float), rtol=1e-6,
            err_msg=rh.names[i],
        )


def test_where_ts_far_outside_grid(inst, rng):
    """Cell-aligned WHERE ts bounds billions of cells away from the grid
    must not overflow the int32 device scalars."""
    inst.execute_sql(
        "create table tiny (ts timestamp time index, host string "
        "primary key, x double)"
    )
    tab = inst.catalog.table("public", "tiny")
    ts = np.arange(2000, dtype=np.int64)  # 1ms interval -> res=1ms
    tab.write({"host": np.asarray(["a"] * 2000, object)}, ts,
              {"x": rng.random(2000)})
    inst.query_engine = QueryEngine(prefer_device=True)
    r = inst.sql(
        "SELECT ts, max(x) RANGE '1s' FROM tiny WHERE ts >= 6000000000 "
        "ALIGN '1s' BY ()"
    )
    assert r.num_rows == 0
    r = inst.sql(
        "SELECT ts, max(x) RANGE '1s' FROM tiny WHERE ts < 6000000000 "
        "ALIGN '1s' BY () ORDER BY ts"
    )
    assert r.num_rows == 2


def test_byte_budget_gates_build_and_growth(cpu):
    """Cache HBM accounting: too-small budgets refuse the build (host
    fallback); growth of a cached entry respects the aggregate budget."""
    from greptimedb_tpu.query.device_range import DeviceRangeCache

    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    inst.query_engine.range_cache = DeviceRangeCache(byte_budget=1000)
    r = inst.sql(QUERIES[0])
    assert inst.query_engine.last_exec_path == "host"  # refused: too big
    assert r.num_rows > 0

    # budget fits the avg-states build but not growth to first/last states
    inst.query_engine = QueryEngine(prefer_device=True)
    cache = inst.query_engine.range_cache
    r1 = inst.sql(QUERIES[0])
    assert inst.query_engine.last_exec_path == "device"
    entry = next(iter(cache._entries.values()))
    assert cache.total_bytes() == entry.bytes() > 0
    cache.byte_budget = entry.bytes()  # no headroom left
    inst.sql(
        "SELECT ts, host, last_value(u) RANGE '10s' FROM cpu "
        "ALIGN '10s' BY (host)"
    )
    assert inst.query_engine.last_exec_path == "host"  # growth refused
    assert cache.total_bytes() <= cache.byte_budget


def test_device_range_empty_matcher(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    r = inst.sql(
        "SELECT ts, host, avg(u) RANGE '10s' FROM cpu WHERE host = 'nope' "
        "ALIGN '10s' BY (host)"
    )
    assert r.num_rows == 0
