"""Device RANGE execution (query/device_range.py) vs the host path.

The device path runs the same RANGE plans over HBM-resident per-cell
partial-state grids (the page-cache analog of the reference's hot datanode,
/root/reference/src/query/src/range_select/plan.rs); results must agree
with the host NumPy path up to f32 accumulation.
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query.executor import QueryEngine


@pytest.fixture
def inst(tmp_path):
    i = Standalone(str(tmp_path))
    yield i
    i.close()


@pytest.fixture
def cpu(inst, rng):
    inst.execute_sql(
        "create table cpu (ts timestamp time index, host string primary key,"
        " region string primary key, u double, v double)"
    )
    n_hosts, t = 16, 400
    tab = inst.catalog.table("public", "cpu")
    ts = np.tile(np.arange(t) * 1000, n_hosts).astype(np.int64)
    hosts = np.repeat([f"h{i}" for i in range(n_hosts)], t).astype(object)
    regions = np.repeat(
        [f"r{i % 3}" for i in range(n_hosts)], t
    ).astype(object)
    u = rng.random(n_hosts * t) * 100
    v = rng.random(n_hosts * t) * 10
    valid = rng.random(n_hosts * t) > 0.05
    tab.write({"host": hosts, "region": regions}, ts, {"u": u, "v": v},
              field_valid={"u": valid})
    return inst


QUERIES = [
    "SELECT ts, host, avg(u) RANGE '10s' FROM cpu ALIGN '10s' BY (host) "
    "ORDER BY ts, host",
    "SELECT ts, region, sum(u) RANGE '20s', max(v) RANGE '20s', "
    "min(u) RANGE '20s' FROM cpu ALIGN '10s' BY (region) "
    "ORDER BY ts, region",
    "SELECT ts, count(u) RANGE '30s', count(*) RANGE '30s' FROM cpu "
    "ALIGN '30s' BY () ORDER BY ts",
    "SELECT ts, host, last_value(u) RANGE '25s', first_value(v) RANGE '25s' "
    "FROM cpu ALIGN '5s' BY (host) ORDER BY ts, host LIMIT 400",
    "SELECT ts, host, stddev(u) RANGE '40s' FROM cpu "
    "WHERE ts >= 100000 AND ts < 300000 ALIGN '20s' BY (host) "
    "ORDER BY ts, host",
    "SELECT ts, region, avg(u) RANGE '10s' FILL PREV FROM cpu "
    "WHERE host != 'h3' ALIGN '10s' BY (region) ORDER BY ts, region",
    "SELECT ts, avg(u) RANGE '1m' FILL LINEAR FROM cpu WHERE host = 'h1' "
    "ALIGN '30s' ORDER BY ts",
    "SELECT ts, host, var_pop(u) RANGE '30s', avg(v) RANGE '30s' AS av "
    "FROM cpu ALIGN '15s' BY (host) HAVING av > 4 ORDER BY ts, host",
]


def _compare(rh, rd, q):
    assert rh.names == rd.names
    assert rh.num_rows == rd.num_rows, q
    for i in range(len(rh.names)):
        a, b = rh.cols[i], rd.cols[i]
        assert (a.valid_mask == b.valid_mask).all(), (q, rh.names[i])
        if a.values.dtype == object:
            assert (a.values == b.values).all(), (q, rh.names[i])
        else:
            m = a.valid_mask
            assert np.allclose(
                np.asarray(a.values, float)[m],
                np.asarray(b.values, float)[m],
                rtol=2e-4, atol=1e-3,
            ), (q, rh.names[i])


@pytest.mark.parametrize("q", QUERIES)
def test_device_range_matches_host(cpu, q):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=False)
    rh = inst.sql(q)
    inst.query_engine = QueryEngine(prefer_device=True)
    rd = inst.sql(q)
    assert inst.query_engine.last_exec_path == "device", q
    _compare(rh, rd, q)


def test_device_range_cache_hit_and_invalidation(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    q = QUERIES[0]
    r1 = inst.sql(q)
    cache = inst.query_engine.range_cache
    assert len(cache._entries) == 1
    entry = next(iter(cache._entries.values()))
    r2 = inst.sql(q)
    assert next(iter(cache._entries.values())) is entry  # reused
    assert r1.rows() == r2.rows()
    # a write bumps the data version and invalidates the entry
    inst.execute_sql(
        "insert into cpu (ts, host, region, u, v) "
        "values (400000, 'h0', 'r0', 50.0, 5.0)"
    )
    r3 = inst.sql(q)
    entry2 = next(iter(cache._entries.values()))
    assert entry2 is not entry
    assert r3.num_rows == r1.num_rows + 1


def test_device_range_falls_back_on_residual(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    # residual filter on a field value is not expressible over partials
    r = inst.sql(
        "SELECT ts, host, avg(u) RANGE '10s' FROM cpu WHERE v > 5 "
        "ALIGN '10s' BY (host) ORDER BY ts, host"
    )
    assert inst.query_engine.last_exec_path == "host"
    assert r.num_rows > 0


def test_device_range_empty_matcher(cpu):
    inst = cpu
    inst.query_engine = QueryEngine(prefer_device=True)
    r = inst.sql(
        "SELECT ts, host, avg(u) RANGE '10s' FROM cpu WHERE host = 'nope' "
        "ALIGN '10s' BY (host)"
    )
    assert r.num_rows == 0
