"""Tier-1 lint gate: `greptimedb_tpu/` must be gtlint-clean.

The linter runs over the whole package with the checked-in baseline
(greptimedb_tpu/tools/lint/baseline.json). New findings, stale
baseline entries, and unparseable files all fail — the same contract
as `python -m greptimedb_tpu.tools.lint greptimedb_tpu/` exiting 0.
"""

from __future__ import annotations

import os

from greptimedb_tpu.tools.lint import run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "greptimedb_tpu")


def _fmt(findings):
    return "\n".join(
        f"  {f['path']}:{f['line']}: {f['rule']} {f['message']}"
        for f in findings
    )


def test_package_is_lint_clean():
    # findings are repo-root-anchored (runner._norm_path), so no chdir
    res = run([PKG])
    assert not res["errors"], f"unparseable files: {res['errors']}"
    assert res["counts"]["new"] == 0, (
        "new gtlint findings (fix them, suppress with a justified "
        "`# gtlint: disable=GTxxx`, or — for grandfathered debt — "
        "add a baseline entry):\n" + _fmt(res["findings"])
    )
    assert res["counts"]["stale_baseline"] == 0, (
        "stale baseline entries (the violation is gone — remove them "
        f"from the baseline file): {res['stale_baseline']}"
    )


def test_dataflow_rules_in_gate():
    """GT023-GT027 (the device-contract verifier) must be registered
    and enabled in the default run — the tier-1 gate covers them with
    EMPTY baselines, not as an opt-in select."""
    from greptimedb_tpu.tools.lint import Baseline
    from greptimedb_tpu.tools.lint.core import all_rules
    from greptimedb_tpu.tools.lint.runner import DEFAULT_BASELINE

    rules = all_rules()
    for rid in ("GT023", "GT024", "GT025", "GT026", "GT027"):
        assert rid in rules, f"{rid} missing from the registry"
        assert rules[rid].example_pos and rules[rid].example_neg
    base = Baseline.load(DEFAULT_BASELINE)
    dataflow_debt = [e for e in base.entries
                     if e.get("rule", "") >= "GT023"]
    assert dataflow_debt == [], (
        "GT023-GT027 ship with empty baselines — fix or suppress "
        f"with a contract comment instead: {dataflow_debt}"
    )


def test_contract_rules_in_gate():
    """GT028-GT032 (the whole-program wire/config/metric contract
    verifier) must be registered and enabled in the default run — the
    tier-1 gate covers them with EMPTY baselines, not as an opt-in
    select."""
    from greptimedb_tpu.tools.lint import Baseline
    from greptimedb_tpu.tools.lint.core import all_rules
    from greptimedb_tpu.tools.lint.runner import DEFAULT_BASELINE

    rules = all_rules()
    for rid in ("GT028", "GT029", "GT030", "GT031", "GT032"):
        assert rid in rules, f"{rid} missing from the registry"
        assert rules[rid].example_pos and rules[rid].example_neg
    base = Baseline.load(DEFAULT_BASELINE)
    contract_debt = [e for e in base.entries
                     if e.get("rule", "") >= "GT028"]
    assert contract_debt == [], (
        "GT028-GT032 ship with empty baselines — fix or suppress "
        f"with a contract comment instead: {contract_debt}"
    )


def test_index_rule_in_gate():
    """GT033 (full-label-plane predicate — the secondary-index
    discipline) must be registered and enabled in the default run
    with an EMPTY baseline."""
    from greptimedb_tpu.tools.lint import Baseline
    from greptimedb_tpu.tools.lint.core import all_rules
    from greptimedb_tpu.tools.lint.runner import DEFAULT_BASELINE

    rules = all_rules()
    assert "GT033" in rules, "GT033 missing from the registry"
    assert rules["GT033"].example_pos and rules["GT033"].example_neg
    base = Baseline.load(DEFAULT_BASELINE)
    debt = [e for e in base.entries if e.get("rule") == "GT033"]
    assert debt == [], (
        "GT033 ships with an empty baseline — route the matcher "
        f"through the index package instead: {debt}"
    )


def test_baseline_stays_near_empty():
    """The baseline exists to absorb grandfathered debt during a rule
    rollout, not to grow. Keep it near-empty; raising this cap needs
    a README justification."""
    from greptimedb_tpu.tools.lint import Baseline
    from greptimedb_tpu.tools.lint.runner import DEFAULT_BASELINE

    base = Baseline.load(DEFAULT_BASELINE)
    assert len(base.entries) <= 5, (
        f"baseline has {len(base.entries)} entries; pay down the debt "
        "instead of growing it"
    )
