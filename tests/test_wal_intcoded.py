"""Int-coded WAL payloads (fmt 2): replay reconstructs sids via intern
deltas instead of re-interning tag strings (VERDICT r2 task #3)."""

import numpy as np

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.storage.series import SeriesRegistry


def test_intern_rows_delta_orders_and_dedups():
    reg = SeriesRegistry(["host", "dc"])
    sids, new = reg.intern_rows_delta([
        np.asarray(["a", "b", "a"], object),
        np.asarray(["x", "x", "x"], object),
    ])
    assert sids.tolist() == [0, 1, 0]
    assert new == [(0, ["a", "x"]), (1, ["b", "x"])]
    # second batch: one repeat, one new
    sids2, new2 = reg.intern_rows_delta([
        np.asarray(["b", "c"], object),
        np.asarray(["x", "y"], object),
    ])
    assert sids2.tolist() == [1, 2]
    assert new2 == [(2, ["c", "y"])]


def test_ensure_series_idempotent_and_gap_checked():
    reg = SeriesRegistry(["host"])
    reg.ensure_series(0, ["a"])
    reg.ensure_series(0, ["a"])  # idempotent
    reg.ensure_series(1, ["b"])
    assert reg.lookup_series({"host": "a"}) == 0
    assert reg.lookup_series({"host": "b"}) == 1
    try:
        reg.ensure_series(5, ["z"])
        raise AssertionError("gap not detected")
    except ValueError:
        pass


def test_skip_wal_series_recoverable_by_later_durable_write(tmp_path):
    """Series interned by a skip_wal bulk load must be reconstructable when
    a later DURABLE write references them: the next WAL entry carries the
    parked intern delta."""
    home = str(tmp_path / "data")
    inst = Standalone(home)
    inst.sql(
        "CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "m")
    # bulk load creates sids 0,1 without durability
    table.write(
        {"host": np.asarray(["h1", "h2"], object)},
        np.asarray([1000, 1000], np.int64),
        {"v": np.asarray([1.0, 2.0])}, skip_wal=True,
    )
    # durable write reuses sid 1 and creates sid 2
    table.write(
        {"host": np.asarray(["h2", "h3"], object)},
        np.asarray([2000, 2000], np.int64),
        {"v": np.asarray([3.0, 4.0])},
    )
    # no close(): simulate a crash (graceful close would flush the
    # memtable and make even the skip_wal rows durable via the SST)
    inst2 = Standalone(home)
    r = inst2.sql("SELECT host, v FROM m ORDER BY host")
    rows = list(zip(r.cols[0].values, r.cols[1].values))
    # durable rows replay with correct tags; skip_wal rows are (by
    # design) lost unless a flush intervened
    assert ("h2", 3.0) in rows and ("h3", 4.0) in rows
    assert {h for h, _ in rows} <= {"h1", "h2", "h3"}
    inst2.close()
    inst.close()


def test_ensure_series_pads_after_add_tag():
    reg = SeriesRegistry(["host"])
    reg.ensure_series(0, ["a"])
    reg.add_tag("dc")
    # replaying a pre-ALTER delta: shorter tag list pads with ""
    reg.ensure_series(1, ["b"])
    assert reg.series_tags(1) == {"host": "b", "dc": ""}
    assert reg.codes_matrix().shape == (2, 2)


def test_wal_fmt2_replay_across_restart(tmp_path):
    home = str(tmp_path / "data")
    inst = Standalone(home)
    inst.sql(
        "CREATE TABLE m (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    table = inst.catalog.table("public", "m")
    # two batches; the second introduces a new series
    table.write(
        {"host": np.asarray(["h1", "h2"], object)},
        np.asarray([1000, 1000], np.int64),
        {"v": np.asarray([1.0, 2.0])},
    )
    table.write(
        {"host": np.asarray(["h2", "h3"], object)},
        np.asarray([2000, 2000], np.int64),
        {"v": np.asarray([3.0, 4.0])},
    )
    inst.close()

    inst2 = Standalone(home)
    r = inst2.sql("SELECT host, v FROM m ORDER BY host, ts")
    rows = list(zip(r.cols[0].values, r.cols[1].values))
    assert rows == [("h1", 1.0), ("h2", 2.0), ("h2", 3.0), ("h3", 4.0)]
    inst2.close()
