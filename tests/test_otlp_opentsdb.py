"""OTLP metrics + OpenTSDB ingest (VERDICT r2 missing-component #7).

The OTLP test encodes a real protobuf ExportMetricsServiceRequest by
hand (wire format per protobuf encoding spec) — the same bytes an
OpenTelemetry SDK exporter sends.
"""

import json
import struct
import urllib.request

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.http import HttpServer


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    yield s
    s.close()


@pytest.fixture()
def http(inst):
    srv = HttpServer(inst, port=0).start()
    yield srv
    srv.stop()


# ---- protobuf wire helpers (writer side, tests only) -----------------

def _tag(fno, wt):
    return bytes([(fno << 3) | wt])


def _varint(v):
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(fno, payload: bytes) -> bytes:
    return _tag(fno, 2) + _varint(len(payload)) + payload


def _kv(key: str, val: str) -> bytes:
    any_value = _ld(1, val.encode())
    return _ld(1, key.encode()) + _ld(2, any_value)


def _number_point(attrs: dict, t_ms: int, value: float) -> bytes:
    p = b""
    for k, v in attrs.items():
        p += _ld(7, _kv(k, v))
    p += _tag(3, 0) + _varint(t_ms * 1_000_000)
    p += _tag(4, 1) + struct.pack("<d", value)
    return p


def _gauge_metric(name: str, points: list[bytes]) -> bytes:
    gauge = b"".join(_ld(1, p) for p in points)
    return _ld(1, name.encode()) + _ld(5, gauge)


def _hist_point(attrs: dict, t_ms: int, counts, bounds, hsum) -> bytes:
    p = b""
    for k, v in attrs.items():
        p += _ld(9, _kv(k, v))
    p += _tag(3, 0) + _varint(t_ms * 1_000_000)
    p += _tag(4, 0) + _varint(sum(counts))
    p += _tag(5, 1) + struct.pack("<d", hsum)
    p += _ld(6, b"".join(struct.pack("<Q", c) for c in counts))
    p += _ld(7, b"".join(struct.pack("<d", b) for b in bounds))
    return p


def _hist_metric(name: str, point: bytes) -> bytes:
    return _ld(1, name.encode()) + _ld(9, _ld(1, point))


def _request(metrics: list[bytes], resource_attrs: dict) -> bytes:
    resource = b"".join(_ld(1, _kv(k, v))
                        for k, v in resource_attrs.items())
    scope_metrics = b"".join(_ld(2, m) for m in metrics)
    rm = _ld(1, resource) + _ld(2, scope_metrics)
    return _ld(1, rm)


def _post(port, path, body, ctype):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": ctype}, method="POST",
    )
    return urllib.request.urlopen(req, timeout=10)


T0 = 1_700_000_000_000


def test_otlp_protobuf_gauge_and_histogram(inst, http):
    body = _request(
        [
            _gauge_metric("system.cpu.Load", [
                _number_point({"core": "0"}, T0, 0.5),
                _number_point({"core": "1"}, T0, 0.75),
            ]),
            _hist_metric("http.server.duration",
                         _hist_point({"route": "/api"}, T0,
                                     [3, 2, 1], [10.0, 50.0], 120.0)),
        ],
        {"service.name": "api"},
    )
    resp = _post(http.port, "/v1/otlp/v1/metrics", body,
                 "application/x-protobuf")
    assert resp.status == 200

    r = inst.sql("SELECT core, greptime_value FROM system_cpu_load "
                 "ORDER BY core")
    rows = [list(x) for x in r.rows()]
    assert rows == [["0", 0.5], ["1", 0.75]]
    # resource attrs become tags
    r = inst.sql("SELECT service_name FROM system_cpu_load LIMIT 1")
    assert r.rows()[0][0] == "api"
    # histogram: cumulative buckets with le, sum + count tables
    r = inst.sql("SELECT le, greptime_value FROM "
                 "http_server_duration_bucket ORDER BY greptime_value")
    rows = [list(x) for x in r.rows()]
    assert rows == [["10.0", 3.0], ["50.0", 5.0], ["+Inf", 6.0]]
    r = inst.sql("SELECT greptime_value FROM http_server_duration_sum")
    assert float(r.rows()[0][0]) == 120.0
    r = inst.sql("SELECT greptime_value FROM http_server_duration_count")
    assert float(r.rows()[0][0]) == 6.0


def test_otlp_protobuf_fixed64_encoding(inst, http):
    """Real SDK exporters encode time_unix_nano as fixed64 (wire type 1)
    and as_int as sfixed64 — not varints."""
    p = _ld(7, _kv("host", "a"))
    p += _tag(3, 1) + struct.pack("<Q", T0 * 1_000_000)   # fixed64 time
    p += _tag(6, 1) + struct.pack("<q", -7)               # sfixed64 int
    body = _request([_ld(1, b"gauge.fixed") + _ld(5, _ld(1, p))], {})
    resp = _post(http.port, "/v1/otlp/v1/metrics", body,
                 "application/x-protobuf")
    assert resp.status == 200
    r = inst.sql("SELECT greptime_value, greptime_timestamp "
                 "FROM gauge_fixed")
    row = list(r.rows()[0])
    assert float(row[0]) == -7.0 and int(row[1]) == T0


def test_otlp_json(inst, http):
    doc = {
        "resourceMetrics": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "js"}},
            ]},
            "scopeMetrics": [{
                "metrics": [{
                    "name": "queue.size",
                    "gauge": {"dataPoints": [{
                        "attributes": [
                            {"key": "q", "value": {"stringValue": "a"}},
                        ],
                        "timeUnixNano": str(T0 * 1_000_000),
                        "asDouble": 17.0,
                    }]},
                }],
            }],
        }],
    }
    resp = _post(http.port, "/v1/otlp/v1/metrics",
                 json.dumps(doc).encode(), "application/json")
    assert resp.status == 200
    r = inst.sql("SELECT q, greptime_value, greptime_timestamp "
                 "FROM queue_size")
    row = list(r.rows()[0])
    assert row[0] == "a" and float(row[1]) == 17.0 and int(row[2]) == T0


def test_opentsdb_put(inst, http):
    points = [
        {"metric": "sys.cpu.user", "timestamp": T0 // 1000,
         "value": 42.5, "tags": {"host": "web01", "dc": "lga"}},
        {"metric": "sys.cpu.user", "timestamp": T0,
         "value": 43.0, "tags": {"host": "web02", "dc": "lga"}},
    ]
    resp = _post(http.port, "/v1/opentsdb/api/put",
                 json.dumps(points).encode(), "application/json")
    assert resp.status == 204
    r = inst.sql('SELECT host, greptime_value, greptime_timestamp '
                 'FROM sys_cpu_user ORDER BY host')
    rows = [list(x) for x in r.rows()]
    # second- and ms-precision timestamps both normalize to ms
    assert rows == [["web01", 42.5, T0], ["web02", 43.0, T0]]

    # single-object flavor + ?details response
    one = {"metric": "sys.mem", "timestamp": T0 // 1000, "value": 1.0}
    resp = _post(http.port, "/v1/opentsdb/api/put?details",
                 json.dumps(one).encode(), "application/json")
    assert resp.status == 200
    assert json.loads(resp.read())["success"] == 1

    # malformed input -> 400
    bad = [{"metric": "m", "timestamp": 1}]  # no value
    try:
        _post(http.port, "/v1/opentsdb/api/put",
              json.dumps(bad).encode(), "application/json")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def _span_pb(trace_id, span_id, name, start_ns, end_ns, attrs=None):
    p = _ld(1, trace_id) + _ld(2, span_id) + _ld(5, name.encode())
    p += _tag(6, 0) + _varint(2)  # SPAN_KIND_SERVER
    p += _tag(7, 1) + struct.pack("<Q", start_ns)
    p += _tag(8, 1) + struct.pack("<Q", end_ns)
    for k, v in (attrs or {}).items():
        p += _ld(9, _kv(k, v))
    p += _ld(15, _ld(2, b"boom") + (_tag(3, 0) + _varint(2)))  # ERROR
    return p


def test_otlp_traces(inst, http):
    span = _span_pb(b"\xab" * 16, b"\xcd" * 8, "GET /api",
                    T0 * 1_000_000, (T0 + 25) * 1_000_000,
                    {"http.method": "GET"})
    scope_spans = _ld(1, _ld(1, b"my-lib")) + _ld(2, span)
    resource = _ld(1, _kv("service.name", "checkout"))
    body = _ld(1, _ld(1, resource) + _ld(2, scope_spans))
    resp = _post(http.port, "/v1/otlp/v1/traces", body,
                 "application/x-protobuf")
    assert resp.status == 200
    r = inst.sql(
        "SELECT service_name, trace_id, span_name, span_kind, "
        "span_status_code, duration_nano, greptime_timestamp "
        "FROM traces_preview_v01"
    )
    row = list(r.rows()[0])
    assert row[0] == "checkout"
    assert row[1] == "ab" * 16
    assert row[2] == "GET /api" and row[3] == "SPAN_KIND_SERVER"
    assert row[4] == "STATUS_CODE_ERROR"
    assert float(row[5]) == 25_000_000.0
    assert int(row[6]) == T0
    # append-mode: a second identical-ts span must NOT dedup away
    resp = _post(http.port, "/v1/otlp/v1/traces", body,
                 "application/x-protobuf")
    r = inst.sql("SELECT count(*) FROM traces_preview_v01")
    assert int(r.rows()[0][0]) == 2


def test_otlp_logs(inst, http):
    rec = _tag(1, 1) + struct.pack("<Q", T0 * 1_000_000)
    rec += _tag(2, 0) + _varint(17)            # SEVERITY_NUMBER_ERROR
    rec += _ld(3, b"ERROR")
    rec += _ld(5, _ld(1, b"disk on fire"))     # body AnyValue string
    rec += _ld(6, _kv("k8s.pod", "web-1"))
    scope_logs = _ld(1, _ld(1, b"applog")) + _ld(2, rec)
    resource = _ld(1, _kv("service.name", "api"))
    body = _ld(1, _ld(1, resource) + _ld(2, scope_logs))
    resp = _post(http.port, "/v1/otlp/v1/logs", body,
                 "application/x-protobuf")
    assert resp.status == 200
    r = inst.sql(
        "SELECT service_name, severity_text, body, greptime_timestamp "
        "FROM opentelemetry_logs"
    )
    row = list(r.rows()[0])
    assert row == ["api", "ERROR", "disk on fire", T0]
    r = inst.sql("SELECT log_attributes FROM opentelemetry_logs")
    assert "web-1" in r.rows()[0][0]
    # fulltext-style filtering works over the body
    r = inst.sql("SELECT count(*) FROM opentelemetry_logs "
                 "WHERE matches(body, 'disk AND fire')")
    assert int(r.rows()[0][0]) == 1
