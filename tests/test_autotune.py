"""gtune adaptive control plane (greptimedb_tpu/autotune/).

Knob registry validation and the single-write-path audit log,
controller convergence on simulated sensors (monotone approach, no
oscillation past the hysteresis band), guardrail semantics (step
clamp, cooldown spacing, freeze/disable), per-controller failure
isolation, and cross-surface agreement: the same decisions at the
same values on information_schema.autotune_decisions, ADMIN
set_config, and the gtpu_autotune_* metrics.
"""

from __future__ import annotations

import json
import time

import pytest

from greptimedb_tpu.autotune import (
    AdmissionConcurrencyController,
    AutotuneRuntime,
    CompactionPacingController,
    Guardrails,
    HbmBudgetController,
    KnobRegistry,
    KnobSpec,
    PlannerThresholdController,
)
from greptimedb_tpu.errors import InvalidArgumentError
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.telemetry.metrics import global_registry


def _metric_value(name: str, *labels: str) -> float:
    """Current value of one labeled child (sum of all children when
    no labels given); 0.0 when the metric never registered."""
    try:
        metric = global_registry.get(name)
    except KeyError:
        return 0.0
    total = 0.0
    for key, child in metric._snapshot():
        if not labels or tuple(labels) == tuple(key):
            total += child.value
    return total


def _knob(path: str, kind=int, lo=0.0, hi=float(1 << 40), init=0,
          pool: str | None = None):
    """A KnobSpec over a one-slot box — the simulated live object."""
    box = {"v": kind(init)}
    spec = KnobSpec(
        path, kind, lo, hi, f"test knob {path}",
        getter=lambda: box["v"],
        setter=lambda nv: box.__setitem__("v", nv),
        pool=pool,
    )
    return spec, box


def _registry(*specs) -> KnobRegistry:
    reg = KnobRegistry()
    for s in specs:
        reg.register(s)
    return reg


# ---------------------------------------------------------------------------
# knob registry: the single validated write path
# ---------------------------------------------------------------------------

def test_registry_set_applies_logs_and_publishes():
    spec, box = _knob("scheduler.max_concurrency", init=4)
    reg = _registry(spec)
    before = _metric_value("gtpu_autotune_decisions_total", "admission")

    old, new = reg.set("scheduler.max_concurrency", 8,
                       source="admission", evidence={"queued": 3})
    assert (old, new) == (4, 8)
    assert box["v"] == 8 and reg.get("scheduler.max_concurrency") == 8
    (ch,) = reg.changes()
    assert (ch.controller, ch.knob, ch.old, ch.new) == (
        "admission", "scheduler.max_concurrency", 4, 8)
    assert ch.evidence == {"queued": 3}
    assert json.loads(ch.to_doc()["evidence"]) == {"queued": 3}
    assert _metric_value("gtpu_autotune_knob_value",
                         "scheduler.max_concurrency") == 8.0
    assert _metric_value("gtpu_autotune_decisions_total",
                         "admission") == before + 1


def test_registry_noop_write_is_not_logged():
    spec, _ = _knob("k", init=8)
    reg = _registry(spec)
    assert reg.set("k", 8) == (8, 8)
    assert reg.changes() == [] and reg.decision_count() == 0


def test_registry_type_coercion():
    ispec, ibox = _knob("i", kind=int, init=1)
    fspec, fbox = _knob("f", kind=float, init=1.0)
    bspec, bbox = _knob("b", kind=bool, lo=None, hi=None, init=False)
    reg = _registry(ispec, fspec, bspec)
    reg.set("i", "8")
    assert ibox["v"] == 8 and isinstance(ibox["v"], int)
    reg.set("i", 16.0)          # integral float is fine
    assert ibox["v"] == 16
    reg.set("f", "2.5")
    assert fbox["v"] == 2.5
    for truthy in (True, 1, "true", "1"):
        reg.set("b", False)
        reg.set("b", truthy)
        assert bbox["v"] is True
    reg.set("b", "false")
    assert bbox["v"] is False


def test_registry_rejects_bad_values():
    ispec, ibox = _knob("i", kind=int, lo=1, hi=64, init=8)
    bspec, _ = _knob("b", kind=bool, lo=None, hi=None, init=False)
    reg = _registry(ispec, bspec)
    with pytest.raises(InvalidArgumentError):
        reg.set("no.such.knob", 1)
    with pytest.raises(InvalidArgumentError):
        reg.set("i", 8.5)            # fractional on an int knob
    with pytest.raises(InvalidArgumentError):
        reg.set("i", True)           # bool is not an int here
    with pytest.raises(InvalidArgumentError):
        reg.set("i", "not-a-number")
    with pytest.raises(InvalidArgumentError):
        reg.set("i", 0)              # below lo
    with pytest.raises(InvalidArgumentError):
        reg.set("i", 65)             # above hi
    with pytest.raises(InvalidArgumentError):
        reg.set("b", "maybe")
    assert ibox["v"] == 8            # nothing applied
    assert reg.changes() == []


def test_registry_snapshot_surface():
    spec, _ = _knob("result_cache.bytes", init=1024, pool="result_cache")
    reg = _registry(spec)
    (row,) = reg.snapshot()
    assert row["knob"] == "result_cache.bytes"
    assert row["value"] == 1024 and row["kind"] == "int"
    assert row["pool"] == "result_cache"


# ---------------------------------------------------------------------------
# admission controller on a simulated sensor
# ---------------------------------------------------------------------------

def _admission(init_limit, sense, **rails):
    spec, box = _knob("scheduler.max_concurrency", lo=0, hi=65536,
                      init=init_limit)
    reg = _registry(spec)
    c = AdmissionConcurrencyController(
        reg, sense, rails=Guardrails(**rails) if rails else None)
    return c, reg, box


def test_admission_converges_up_without_oscillation():
    """Queue pressure until the limit covers demand (8 slots), then
    the signal goes quiet: the limit must ramp monotonically, settle,
    and never oscillate past the hysteresis band."""
    def sense():
        limit = box["v"]
        if limit < 8:
            return {"running": limit, "queued": 8 - limit,
                    "mean_cost_ms": 10.0, "queue_p99_ms": 50.0}
        return {"running": 8, "queued": 0,
                "mean_cost_ms": 10.0, "queue_p99_ms": 0.5}

    c, reg, box = _admission(2, sense, cooldown_ticks=1)
    trajectory = [box["v"]]
    for _ in range(40):
        c.tick()
        trajectory.append(box["v"])
    # monotone ramp: never a downward move during or after convergence
    assert all(b >= a for a, b in zip(trajectory, trajectory[1:]))
    final = trajectory[-1]
    assert final >= 8
    # settled: the last ticks produced no movement at all
    assert trajectory[-5:] == [final] * 5
    # every applied step respected the relative clamp
    for ch in reg.changes():
        assert ch.new <= int(round(ch.old * (1 + c.rails.step))) + 1


def test_admission_idle_scale_down_is_step_clamped():
    c, reg, box = _admission(
        100, lambda: {"running": 2, "queued": 0,
                      "mean_cost_ms": 5.0, "queue_p99_ms": 0.0},
        cooldown_ticks=1)
    c.tick()
    # target is running+1 = 3, but one decision may shrink at most 25%
    assert box["v"] == 75
    c.tick()
    assert box["v"] == 56  # int(round(75 * 0.75))


def test_admission_never_enables_limiting_on_unlimited():
    c, reg, box = _admission(
        0, lambda: {"running": 50, "queued": 500,
                    "mean_cost_ms": 10.0, "queue_p99_ms": 900.0})
    assert c.tick() == 0 and box["v"] == 0 and reg.changes() == []


def test_admission_cheap_queue_wait_is_not_pressure():
    # statements queue briefly but wait far less than one service
    # time: adding slots would not help; hold
    c, reg, box = _admission(
        4, lambda: {"running": 4, "queued": 1,
                    "mean_cost_ms": 100.0, "queue_p99_ms": 2.0})
    assert c.tick() == 0 and box["v"] == 4


def test_cooldown_spaces_decisions():
    c, reg, box = _admission(
        2, lambda: {"running": 2, "queued": 9,
                    "mean_cost_ms": 10.0, "queue_p99_ms": 80.0},
        cooldown_ticks=3)
    change_ticks = []
    for t in range(1, 13):
        if c.tick():
            change_ticks.append(t)
    assert change_ticks  # pressure did move the knob
    gaps = [b - a for a, b in zip(change_ticks, change_ticks[1:])]
    assert gaps and all(g >= 3 for g in gaps)


def test_disabled_controller_never_reads_its_sensor():
    calls = []

    def sense():
        calls.append(1)
        return {"running": 0, "queued": 9, "mean_cost_ms": 1.0,
                "queue_p99_ms": 50.0}

    c, reg, box = _admission(2, sense)
    c.enabled = False
    assert all(c.tick() == 0 for _ in range(5))
    assert calls == [] and box["v"] == 2


# ---------------------------------------------------------------------------
# planner controller
# ---------------------------------------------------------------------------

def _planner(init_series, init_rows, sense, **rails):
    s1, b1 = _knob("mesh.shard_min_series", lo=1, hi=1 << 24,
                   init=init_series)
    s2, b2 = _knob("mesh.shard_min_rows", lo=1, hi=1 << 30,
                   init=init_rows)
    reg = _registry(s1, s2)
    c = PlannerThresholdController(
        reg, sense, rails=Guardrails(**rails) if rails else None)
    return c, reg, b1, b2


def test_planner_moves_both_thresholds_together():
    c, reg, b1, b2 = _planner(
        4096, 1 << 16,
        lambda: {"shard_ms": 10.0, "replicate_ms": 20.0})  # shard wins
    assert c.tick() == 2
    assert b1["v"] == int(round(4096 * 0.75))
    assert b2["v"] == int(round((1 << 16) * 0.75))
    # replicate wins -> thresholds go back up
    c2, reg2, r1, r2 = _planner(
        4096, 1 << 16,
        lambda: {"shard_ms": 20.0, "replicate_ms": 10.0})
    assert c2.tick() == 2
    assert r1["v"] == int(round(4096 * 1.25))


def test_planner_holds_inside_hysteresis_band():
    c, reg, b1, b2 = _planner(
        4096, 1 << 16,
        lambda: {"shard_ms": 10.0, "replicate_ms": 11.0})  # 10% apart
    assert c.tick() == 0 and b1["v"] == 4096 and reg.changes() == []


def test_planner_converges_to_break_even_threshold():
    """Simulated system whose shard speedup is proportional to the
    threshold (break-even at 1024): the controller must walk the
    threshold down into the hysteresis band around 1024 and stop."""
    OPT = 1024

    def sense():
        return {"shard_ms": 10.0,
                "replicate_ms": 10.0 * (b1["v"] / OPT)}

    c, reg, b1, b2 = _planner(8192, 8192 * 64, sense,
                              cooldown_ticks=1)
    trajectory = [b1["v"]]
    for _ in range(60):
        c.tick()
        trajectory.append(b1["v"])
    assert all(b <= a for a, b in zip(trajectory, trajectory[1:]))
    final = trajectory[-1]
    assert OPT * (1 - c.rails.band) <= final <= OPT * (1 + c.rails.band)
    assert trajectory[-5:] == [final] * 5  # no oscillation at the end


# ---------------------------------------------------------------------------
# HBM budget controller
# ---------------------------------------------------------------------------

def _hbm_pools(sessions_bytes, result_bytes):
    s1, b1 = _knob("sessions.hbm_bytes", lo=0, init=sessions_bytes,
                   pool="sessions")
    s2, b2 = _knob("result_cache.bytes", lo=0, init=result_bytes,
                   pool="result_cache")
    reg = _registry(s1, s2)
    return reg, b1, b2


def _pool_sig(reg, knob, pool, *, misses_d, evictions_d, hits_d=0):
    return {"knob": knob, "pool": pool, "budget": int(reg.get(knob)),
            "bytes": int(reg.get(knob)), "hits_d": hits_d,
            "misses_d": misses_d, "evictions_d": evictions_d}


def test_hbm_moves_budget_toward_miss_pressure_conserving_total():
    reg, sess, res = _hbm_pools(8 << 20, 1 << 20)

    def sense():
        return [
            _pool_sig(reg, "sessions.hbm_bytes", "sessions",
                      misses_d=0, evictions_d=0, hits_d=100),
            _pool_sig(reg, "result_cache.bytes", "result_cache",
                      misses_d=500, evictions_d=50),
        ]

    c = HbmBudgetController(reg, sense, rails=Guardrails())
    total = sess["v"] + res["v"]
    assert c.tick() == 2
    assert sess["v"] + res["v"] == total      # bytes conserved exactly
    assert res["v"] > (1 << 20) and sess["v"] < (8 << 20)
    moved = res["v"] - (1 << 20)
    assert moved >= HbmBudgetController.MIN_TRANSFER
    # step-clamped against the smaller budget
    assert moved <= max(HbmBudgetController.MIN_TRANSFER,
                        int((1 << 20) * c.rails.step))
    assert {ch.controller for ch in reg.changes()} == {"hbm"}


def test_hbm_holds_without_evictions_or_contrast():
    reg, sess, res = _hbm_pools(4 << 20, 4 << 20)
    # misses but no evictions: pool is not budget-starved
    c = HbmBudgetController(reg, lambda: [
        _pool_sig(reg, "sessions.hbm_bytes", "sessions",
                  misses_d=0, evictions_d=0),
        _pool_sig(reg, "result_cache.bytes", "result_cache",
                  misses_d=100, evictions_d=0),
    ])
    assert c.tick() == 0
    # both pools equally warm: not enough contrast to act on
    c2 = HbmBudgetController(reg, lambda: [
        _pool_sig(reg, "sessions.hbm_bytes", "sessions",
                  misses_d=100, evictions_d=10),
        _pool_sig(reg, "result_cache.bytes", "result_cache",
                  misses_d=100, evictions_d=10),
    ])
    assert c2.tick() == 0
    assert sess["v"] == 4 << 20 and res["v"] == 4 << 20


def test_hbm_repeated_ticks_drain_donor_only_to_its_floor():
    reg, sess, res = _hbm_pools(1 << 20, 1 << 20)

    def sense():
        return [
            _pool_sig(reg, "sessions.hbm_bytes", "sessions",
                      misses_d=0, evictions_d=0),
            _pool_sig(reg, "result_cache.bytes", "result_cache",
                      misses_d=500, evictions_d=50),
        ]

    c = HbmBudgetController(reg, sense,
                            rails=Guardrails(cooldown_ticks=1))
    total = sess["v"] + res["v"]
    for _ in range(200):
        c.tick()
    assert sess["v"] + res["v"] == total
    assert sess["v"] >= 0                     # never below the bound
    assert res["v"] <= total


# ---------------------------------------------------------------------------
# compaction pacing controller
# ---------------------------------------------------------------------------

def _compaction(workers, trigger, sense, baseline=1, **rails):
    s1, b1 = _knob("compaction.workers", lo=1, hi=64, init=workers)
    s2, b2 = _knob("compaction.l1_trigger_files", lo=2, hi=256,
                   init=trigger)
    reg = _registry(s1, s2)
    c = CompactionPacingController(
        reg, sense, baseline_workers=baseline,
        rails=Guardrails(**rails) if rails else None)
    return c, reg, b1, b2


def test_compaction_tightens_trigger_under_read_amp():
    c, reg, workers, trigger = _compaction(
        1, 8, lambda: {"read_amp": 20, "ingest_rows_per_s": 100.0})
    assert c.tick() == 1
    assert trigger["v"] == 6 and workers["v"] == 1


def test_compaction_widens_pool_when_trigger_at_floor():
    c, reg, workers, trigger = _compaction(
        1, 2, lambda: {"read_amp": 20, "ingest_rows_per_s": 100.0})
    assert c.tick() == 1
    assert trigger["v"] == 2 and workers["v"] == 2


def test_compaction_gives_width_back_when_merges_catch_up():
    c, reg, workers, trigger = _compaction(
        4, 8, lambda: {"read_amp": 1, "ingest_rows_per_s": 0.0},
        baseline=2, cooldown_ticks=1)
    for _ in range(10):
        c.tick()
    assert workers["v"] == 2   # back to baseline, never below it


# ---------------------------------------------------------------------------
# runtime: freeze / disable / isolation / lifecycle
# ---------------------------------------------------------------------------

class _Recorder:
    """Controller stub: counts ticks, applies one change per tick."""

    name = "recorder"

    def __init__(self, reg, knob):
        self.reg, self.knob = reg, knob
        self.enabled = True
        self.rails = Guardrails()
        self.ticks = 0

    def tick(self):
        self.ticks += 1
        cur = self.reg.get(self.knob)
        self.reg.set(self.knob, cur + 1, source=self.name,
                     evidence={"tick": self.ticks})
        return 1


class _Raiser:
    name = "raiser"
    enabled = True
    rails = Guardrails()
    raised = 0

    def tick(self):
        self.raised += 1
        raise RuntimeError("sensor backend went away")


def test_runtime_disabled_is_bit_for_bit_noop():
    spec, box = _knob("k", init=5)
    reg = _registry(spec)
    rec = _Recorder(reg, "k")
    rt = AutotuneRuntime(reg, [rec], enabled=False)
    ticks_before = _metric_value("gtpu_autotune_ticks_total")
    assert all(rt.tick_once() == 0 for _ in range(5))
    assert rec.ticks == 0 and box["v"] == 5 and reg.changes() == []
    assert _metric_value("gtpu_autotune_ticks_total") == ticks_before


def test_runtime_frozen_ticks_but_never_moves():
    spec, box = _knob("k", init=5)
    reg = _registry(spec)
    rec = _Recorder(reg, "k")
    rt = AutotuneRuntime(reg, [rec], enabled=True)
    rt.freeze(True)
    ticks_before = _metric_value("gtpu_autotune_ticks_total")
    assert rt.tick_once() == 0
    assert _metric_value("gtpu_autotune_frozen") == 1.0
    assert _metric_value("gtpu_autotune_ticks_total") == ticks_before + 1
    assert rec.ticks == 0 and box["v"] == 5
    rt.freeze(False)
    assert _metric_value("gtpu_autotune_frozen") == 0.0
    assert rt.tick_once() == 1 and box["v"] == 6


def test_runtime_isolates_a_raising_controller():
    spec, box = _knob("k", init=5)
    reg = _registry(spec)
    bad, good = _Raiser(), _Recorder(reg, "k")
    rt = AutotuneRuntime(reg, [bad, good], enabled=True)
    errs_before = _metric_value(
        "gtpu_autotune_controller_errors_total", "raiser")
    assert rt.tick_once() == 1          # the good controller still ran
    assert box["v"] == 6 and bad.raised == 1
    assert _metric_value("gtpu_autotune_controller_errors_total",
                         "raiser") == errs_before + 1
    assert rt.tick_once() == 1          # and the loop survives


def test_runtime_apply_options():
    spec, _ = _knob("k", init=5)
    reg = _registry(spec)
    a, b = _Recorder(reg, "k"), _Recorder(reg, "k")
    a.name, b.name = "admission", "planner"
    rt = AutotuneRuntime(reg, [a, b])
    rt.apply_options({
        "enable": True, "tick_interval_s": 0.25, "planner": False,
        "step": 0.5, "band": 0.05, "cooldown_ticks": 7,
    })
    assert rt.enabled and rt.interval_s == 0.25
    assert a.enabled and not b.enabled
    assert a.rails.step == 0.5 and a.rails.band == 0.05
    assert a.rails.cooldown_ticks == 7


def test_runtime_thread_lifecycle():
    spec, box = _knob("k", init=0)
    reg = _registry(spec)
    rec = _Recorder(reg, "k")
    rt = AutotuneRuntime(reg, [rec], interval_s=0.01, enabled=True)
    rt.start()
    deadline = time.monotonic() + 5.0
    while rec.ticks < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    rt.close()
    assert rec.ticks >= 3
    ticks_at_close = rec.ticks
    time.sleep(0.05)
    assert rec.ticks == ticks_at_close  # loop actually stopped
    rt.close()                           # idempotent


# ---------------------------------------------------------------------------
# Standalone integration: ADMIN + information_schema + metrics agree
# ---------------------------------------------------------------------------

@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


def test_standalone_registers_standard_knob_set(inst):
    assert set(inst.knobs.paths()) >= {
        "scheduler.max_concurrency",
        "mesh.shard_min_series", "mesh.shard_min_rows",
        "sessions.hbm_bytes", "result_cache.bytes",
        "compaction.workers", "compaction.l1_trigger_files",
    }


def test_admin_set_config_round_trip(inst):
    old = inst.knobs.get("scheduler.max_concurrency")
    r = inst.sql("ADMIN set_config('scheduler.max_concurrency', 12)")
    assert r.cols[0].values[0] == f"{old} -> 12"
    assert inst.knobs.get("scheduler.max_concurrency") == 12
    assert inst.scheduler.config.max_concurrency == 12
    (ch,) = inst.knobs.changes()
    assert ch.controller == "admin" and ch.new == 12


def test_admin_set_config_typed_errors(inst):
    with pytest.raises(InvalidArgumentError):
        inst.sql("ADMIN set_config('no.such.knob', 1)")
    with pytest.raises(InvalidArgumentError):
        inst.sql("ADMIN set_config('compaction.workers', 10000)")
    with pytest.raises(InvalidArgumentError):
        inst.sql("ADMIN set_config('compaction.workers', 'lots')")
    assert inst.knobs.changes() == []


def test_admin_freeze_unfreeze(inst):
    assert inst.sql("ADMIN autotune_freeze()").cols[0].values[0] == 1
    assert inst.autotune.frozen
    assert _metric_value("gtpu_autotune_frozen") == 1.0
    assert inst.sql("ADMIN autotune_unfreeze()").cols[0].values[0] == 1
    assert not inst.autotune.frozen
    assert _metric_value("gtpu_autotune_frozen") == 0.0


def test_information_schema_autotune_knobs(inst):
    r = inst.sql("select knob, kind, lower_bound, upper_bound, pool "
                 "from information_schema.autotune_knobs")
    rows = {row[0]: row for row in r.rows()}
    assert "result_cache.bytes" in rows
    knob, kind, lo, hi, pool = rows["result_cache.bytes"]
    assert kind == "int" and lo == 0 and pool == "result_cache"


def test_decisions_agree_across_every_surface(inst):
    """The audit invariant: after a mix of ADMIN and controller
    writes, information_schema.autotune_decisions, the registry
    change log, gtpu_autotune_decisions_total and the knob-value
    gauges all tell the same story."""
    dec_before = _metric_value("gtpu_autotune_decisions_total")
    inst.sql("ADMIN set_config('compaction.workers', 3)")
    inst.sql("ADMIN set_config('result_cache.bytes', 123456)")
    # a controller write through the same path
    inst.knobs.set("compaction.l1_trigger_files", 6,
                   source="compaction", evidence={"read_amp": 20})

    changes = inst.knobs.changes()
    assert len(changes) == 3
    assert inst.knobs.decision_count() == 3
    assert _metric_value("gtpu_autotune_decisions_total") \
        == dec_before + 3

    r = inst.sql("select controller, knob, old_value, new_value, "
                 "evidence from information_schema.autotune_decisions")
    rows = list(r.rows())
    assert len(rows) == 3
    for ch, row in zip(changes, rows):
        assert row[0] == ch.controller and row[1] == ch.knob
        assert row[2] == str(ch.old) and row[3] == str(ch.new)
        assert json.loads(row[4]) == ch.evidence
    # evidence of the controller write survived the JSON round trip
    assert json.loads(rows[2][4]) == {"read_amp": 20}
    # the knob gauges agree with the live values
    for knob in ("compaction.workers", "result_cache.bytes",
                 "compaction.l1_trigger_files"):
        assert _metric_value("gtpu_autotune_knob_value", knob) \
            == float(inst.knobs.get(knob))


def test_standalone_disabled_runtime_is_noop(inst):
    """Default config ships the control plane disabled: a tick must
    not move any knob, log any decision, or read any sensor."""
    assert not inst.autotune.enabled
    before = {p: inst.knobs.get(p) for p in inst.knobs.paths()}
    ticks_before = _metric_value("gtpu_autotune_ticks_total")
    assert inst.autotune.tick_once() == 0
    assert {p: inst.knobs.get(p) for p in inst.knobs.paths()} == before
    assert inst.knobs.changes() == []
    assert _metric_value("gtpu_autotune_ticks_total") == ticks_before


def test_standalone_enabled_tick_survives_and_audits(inst):
    """Flip the runtime on against the REAL sensors: the tick must
    complete (no sensor raises against a live instance) and any
    decision it makes must land in the audit log."""
    inst.autotune.apply_options({"enable": True})
    n = inst.autotune.tick_once()
    assert n == inst.knobs.decision_count()
    for doc in inst.autotune.decisions():
        assert doc["controller"] in ("admission", "planner", "hbm",
                                     "compaction")
        assert json.loads(doc["evidence"]) is not None
