"""gtdev device-contract verifier: dataflow engine + GT023-GT027.

Three layers under test:

1. the abstract-interpretation engine itself (``tools/lint/dataflow``):
   CFG joins, loop re-entry convergence, and the top-element
   conservatism contract (unknown facts must never manufacture
   findings);
2. the five device-contract rules, each with a positive fixture that
   must fire at a known line and a negative twin that must stay
   silent;
3. the ``--explain`` surface: every registered rule's shipped examples
   are linted for real (positive fires, negative is clean), so the
   docs cannot rot.
"""

from __future__ import annotations

import ast
import io
import textwrap

import pytest

from greptimedb_tpu.tools.lint import dataflow
from greptimedb_tpu.tools.lint.core import all_rules
from greptimedb_tpu.tools.lint.runner import explain_rule, lint_source

DATAFLOW_RULES = {"GT023", "GT024", "GT025", "GT026", "GT027"}


def run_lint(src: str, select=None):
    sel = {select} if isinstance(select, str) else select
    act, sup = lint_source("greptimedb_tpu/fixture.py",
                           textwrap.dedent(src), select=sel)
    return act, sup


def rules_hit(src: str, select=None):
    act, _ = run_lint(src, select)
    return [(f.rule, f.line) for f in act]


def analyze(src: str):
    tree = ast.parse(textwrap.dedent(src))
    return tree, dataflow.FileAnalyses(tree)


def value_of_return(tree, analyses, func_name: str) -> dataflow.AV:
    """AV of the expression returned by `func_name`."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            scope = analyses.scope(node)
            for n in ast.walk(node):
                if isinstance(n, ast.Return) and n.value is not None:
                    return scope.value(n.value)
    raise AssertionError(f"no return found in {func_name}")


# ---------------------------------------------------------------------------
# engine: CFG joins
# ---------------------------------------------------------------------------

def test_join_if_else_degrades_disagreeing_dims():
    tree, an = analyze("""
        import jax.numpy as jnp

        def f(flag):
            if flag:
                x = jnp.zeros((8, 128), jnp.float32)
            else:
                x = jnp.zeros((16, 128), jnp.float32)
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.kind == "array"
    # first dim disagrees across the branches -> unknown; the agreeing
    # lane dim and dtype survive the join
    assert av.shape == (None, 128)
    assert av.dtype == "float32"


def test_join_if_else_keeps_agreeing_facts():
    tree, an = analyze("""
        import jax.numpy as jnp

        def f(flag):
            if flag:
                x = jnp.zeros((8, 128), jnp.float32)
            else:
                x = jnp.ones((8, 128), jnp.float32)
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.kind == "array"
    assert av.shape == (8, 128)
    assert av.dtype == "float32"


def test_join_branch_without_assignment_degrades():
    # one path leaves x as the argument (top): the join must not
    # pretend the zeros facts hold unconditionally
    tree, an = analyze("""
        import jax.numpy as jnp

        def f(x, flag):
            if flag:
                x = jnp.zeros((8, 128), jnp.float32)
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.shape is None or None in (av.shape or (None,))


# ---------------------------------------------------------------------------
# engine: loop re-entry convergence
# ---------------------------------------------------------------------------

def test_loop_reentry_widens_and_terminates():
    # total takes 0, 1, 2, ... around the back edge; the fixpoint must
    # converge (finite lattice / visit cap) and must NOT report a
    # single concrete value
    tree, an = analyze("""
        def f(n):
            total = 0
            for i in range(n):
                total = total + 1
            return total
    """)
    av = value_of_return(tree, an, "f")
    assert av.kind in ("int", "top")
    assert av.value is None


def test_loop_invariant_array_facts_survive():
    tree, an = analyze("""
        import jax.numpy as jnp

        def f(n):
            x = jnp.zeros((8, 128), jnp.float32)
            for i in range(n):
                x = x + 1.0
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.kind == "array"
    assert av.shape == (8, 128)
    assert av.dtype == "float32"


def test_while_loop_terminates():
    tree, an = analyze("""
        def f(n):
            x = 1
            while x < n:
                x = x * 2
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.kind in ("int", "top")


# ---------------------------------------------------------------------------
# engine: constants and module scan
# ---------------------------------------------------------------------------

def test_fold_blocks_pin_matches_mesh():
    """KNOWN_CONSTANTS seeds FOLD_BLOCKS for the divisibility rule;
    a drift from the real mesh constant would silently rot GT025."""
    from greptimedb_tpu.parallel import mesh

    assert dataflow.KNOWN_CONSTANTS["FOLD_BLOCKS"] == mesh.FOLD_BLOCKS


def test_module_constant_feeds_function_scope():
    tree, an = analyze("""
        import jax.numpy as jnp

        ROWS = 16

        def f():
            x = jnp.zeros((ROWS, 128), jnp.bfloat16)
            return x
    """)
    av = value_of_return(tree, an, "f")
    assert av.shape == (16, 128)
    assert av.dtype == "bfloat16"


# ---------------------------------------------------------------------------
# engine: top-element conservatism — unknown facts stay silent
# ---------------------------------------------------------------------------

def test_unknown_shapes_produce_no_device_findings():
    # every geometric fact flows from arguments: the verifier knows
    # nothing and must say nothing
    assert rules_hit("""
        import jax
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x, blk, interpret):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec(blk, lambda i: (i, 0))],
                out_specs=pl.BlockSpec(blk, lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=interpret,
            )(x)
    """, DATAFLOW_RULES) == []


def test_unknown_dtype_produces_no_promotion_findings():
    assert rules_hit("""
        import jax

        @jax.jit
        def f(x, y):
            return x + y
    """, DATAFLOW_RULES) == []


# ---------------------------------------------------------------------------
# GT023 BlockSpec tiling
# ---------------------------------------------------------------------------

GT023_POS = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def run(interpret):
        x = jnp.zeros((256, 192), jnp.float32)
        return pl.pallas_call(
            kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((128, 96), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((128, 96), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((256, 192), jnp.float32),
            interpret=interpret,
        )(x)
"""


def test_gt023_positive_misaligned_lane_dim():
    hits = rules_hit(GT023_POS, "GT023")
    # both the in_spec and the out_spec carry the 96-lane block
    assert [h[0] for h in hits] == ["GT023", "GT023"]
    assert hits[0][1] in (13, 14)   # anchored at the in_spec BlockSpec


def test_gt023_positive_sublane_misalignment():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((30, 128), jnp.bfloat16)
            return pl.pallas_call(
                kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((15, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((15, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((30, 128), jnp.bfloat16),
                interpret=interpret,
            )(x)
    """, "GT023")
    # bf16 sublane is 16: a 15-row block needs relayout on every step
    assert [h[0] for h in hits] == ["GT023", "GT023"]


def test_gt023_negative_aligned_and_whole_array():
    assert rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((256, 256), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((128, 256), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 256), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT023") == []
    # a block spanning the WHOLE (known) trailing dim is exempt even
    # when that dim is not a multiple of 128 (Mosaic pads once)
    assert rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((256, 96), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((128, 96), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 96), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 96), jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT023") == []


# ---------------------------------------------------------------------------
# GT024 static VMEM overcommit
# ---------------------------------------------------------------------------

def test_gt024_positive_scratch_overcommit():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, acc):
            o_ref[...] = x_ref[...]

        def run(x, interpret):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                scratch_shapes=[pltpu.VMEM((4096, 4096), jnp.float32)],
                interpret=interpret,
            )(x)
    """, "GT024")
    # 4096*4096*f32 = 64 MiB of scratch alone vs the ~16 MiB core
    assert [h[0] for h in hits] == ["GT024"]


def test_gt024_positive_whole_array_residency():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((8192, 1024), jnp.float32)
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((8192, 1024),
                                               jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT024")
    # no grid: input + output resident whole, 2 * 32 MiB
    assert [h[0] for h in hits] == ["GT024"]


def test_gt024_negative_blocked_and_small():
    assert rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, acc):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((8192, 1024), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(64,),
                in_specs=[pl.BlockSpec((128, 1024), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 1024), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((8192, 1024),
                                               jnp.float32),
                scratch_shapes=[pltpu.VMEM((128, 128), jnp.float32)],
                interpret=interpret,
            )(x)
    """, "GT024") == []


# ---------------------------------------------------------------------------
# GT025 grid x block divisibility
# ---------------------------------------------------------------------------

def test_gt025_positive_indivisible_rows():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            x = jnp.zeros((96, 128), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((96, 128), jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT025")
    # 96 rows cannot be covered by 64-row blocks without a ragged tail
    assert [h[0] for h in hits] == ["GT025", "GT025"]


def test_gt025_positive_fold_blocks_contract():
    # FOLD_BLOCKS is pinned in KNOWN_CONSTANTS: a shape built from it
    # resolves statically, so raggedness against it is detectable
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from greptimedb_tpu.parallel.mesh import FOLD_BLOCKS

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            rows = FOLD_BLOCKS * 100
            x = jnp.zeros((rows, 128), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(3,),
                in_specs=[pl.BlockSpec((96, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((96, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((rows, 128),
                                               jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT025")
    # 8 * 100 = 800 rows; 800 % 96 != 0
    assert [h[0] for h in hits] == ["GT025", "GT025"]


def test_gt025_negative_divisible():
    assert rules_hit("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from greptimedb_tpu.parallel.mesh import FOLD_BLOCKS

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(interpret):
            rows = FOLD_BLOCKS * 96
            x = jnp.zeros((rows, 128), jnp.float32)
            return pl.pallas_call(
                kernel,
                grid=(FOLD_BLOCKS,),
                in_specs=[pl.BlockSpec((96, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((96, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((rows, 128),
                                               jnp.float32),
                interpret=interpret,
            )(x)
    """, "GT025") == []


# ---------------------------------------------------------------------------
# GT026 dtype promotion in device scope
# ---------------------------------------------------------------------------

def test_gt026_positive_astype_wide():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = jnp.zeros((8, 128), jnp.float32)
            return a.astype(jnp.float64)
    """, "GT026")
    assert [h[0] for h in hits] == ["GT026"]
    assert hits[0][1] == 8


def test_gt026_positive_binop_promotes_to_wide():
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = jnp.zeros((8, 128), jnp.int32)
            big = 2 ** 40
            return a + big
    """, "GT026")
    assert [h[0] for h in hits] == ["GT026"]


def test_gt026_positive_dataflow_resolved_creation():
    # the wide dtype arrives through a VARIABLE — the syntactic GT009
    # token scan cannot see it, only the dataflow rule can
    hits = rules_hit("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            dt = jnp.float64
            return jnp.zeros((8, 128), dt)
    """, "GT026")
    assert [h[0] for h in hits] == ["GT026"]


def test_gt026_negative_narrow_and_host_scope():
    assert rules_hit("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = jnp.zeros((8, 128), jnp.float32)
            b = a.astype(jnp.bfloat16)
            return a + b
    """, "GT026") == []
    # host scope: wide numpy math is not the device contract's business
    assert rules_hit("""
        import numpy as np

        def f(x):
            return np.asarray(x, np.float64) * 2.0
    """, "GT026") == []


# ---------------------------------------------------------------------------
# GT027 contextvar read under pool
# ---------------------------------------------------------------------------

def test_gt027_positive_submit_reads_ctxvar():
    hits = rules_hit("""
        from greptimedb_tpu.telemetry import tracing

        def work():
            return tracing.current_span()

        def go(pool):
            pool.submit(work)
    """, "GT027")
    assert [(r, ln) for r, ln in hits] == [("GT027", 8)]


def test_gt027_positive_transitive_read():
    # the read is two call hops below the submitted function
    hits = rules_hit("""
        from greptimedb_tpu.util import deadline

        def leaf():
            deadline.check("leaf")

        def mid():
            leaf()

        def go(pool):
            pool.submit(mid)
    """, "GT027")
    assert [h[0] for h in hits] == ["GT027"]


def test_gt027_negative_parent_captured_and_plain_work():
    # the fix idiom: capture on the submitting thread, rebind inside
    assert rules_hit("""
        from greptimedb_tpu.telemetry import tracing

        def work(parent):
            with tracing.child_span("job", _parent=parent):
                return 1

        def go(pool):
            parent = tracing.current_span()
            pool.submit(work, parent)
    """, "GT027") == []
    # a submitted function that touches no ambient context is fine
    assert rules_hit("""
        def work(n):
            return n * 2

        def go(pool):
            pool.submit(work, 3)
    """, "GT027") == []


# ---------------------------------------------------------------------------
# shipped kernels stay silent
# ---------------------------------------------------------------------------

def test_shipped_kernels_clean_under_dataflow_rules():
    """The three production kernels must produce no ACTIVE GT023-GT027
    findings (contract-commented suppressions are allowed and
    expected: merge_gather's (P, 1) blocks are deliberate)."""
    import os

    from greptimedb_tpu.tools.lint.runner import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    kdir = os.path.join(repo, "greptimedb_tpu", "parallel", "kernels")
    res = lint_paths([kdir], baseline=None, select=DATAFLOW_RULES)
    assert res["findings"] == [], res["findings"]


# ---------------------------------------------------------------------------
# --explain: every rule's shipped examples are real
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rid", sorted(all_rules()))
def test_explain_examples_validate(rid):
    rule = all_rules()[rid]
    assert rule.example_pos, f"{rid} ships no firing example"
    assert rule.example_neg, f"{rid} ships no clean example"
    act, _ = lint_source("greptimedb_tpu/example.py", rule.example_pos,
                         select={rid})
    assert any(f.rule == rid for f in act), (
        f"{rid}'s 'Fires on' example does not fire"
    )
    act, _ = lint_source("greptimedb_tpu/example.py", rule.example_neg,
                         select={rid})
    assert act == [], (
        f"{rid}'s 'Stays silent on' example fires: "
        f"{[(f.rule, f.line) for f in act]}"
    )


def test_explain_cli_known_rule():
    buf = io.StringIO()
    assert explain_rule("gt027", out=buf) == 0
    text = buf.getvalue()
    assert "GT027" in text
    assert "Fires on:" in text
    assert "Stays silent on:" in text
    assert "disable=GT027" in text


def test_explain_cli_unknown_rule_exit_2(capsys):
    assert explain_rule("GT999") == 2
    assert "unknown rule id" in capsys.readouterr().err
