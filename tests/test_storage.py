"""Storage engine: WAL, memtable, SST, region, flush/replay, compaction."""

import numpy as np
import pytest

from greptimedb_tpu.storage import codec
from greptimedb_tpu.storage.compaction import compact_once
from greptimedb_tpu.storage.engine import EngineConfig, TsdbEngine
from greptimedb_tpu.storage.memtable import ColumnarRows, Memtable
from greptimedb_tpu.storage.object_store import FsObjectStore, MemoryObjectStore
from greptimedb_tpu.storage.region import (
    Region,
    RegionMetadata,
    RegionOptions,
    dedup_rows,
)
from greptimedb_tpu.storage.sst import read_sst, write_sst
from greptimedb_tpu.storage.wal import RegionWal


# ----------------------------------------------------------------------
# codec
# ----------------------------------------------------------------------

def test_codec_roundtrip(rng):
    cols = {
        "a": rng.normal(size=10),
        "b": rng.integers(0, 100, 10).astype(np.int64),
        "s": np.asarray(["x", "y", "z"] * 3 + ["w"], dtype=object),
    }
    data = codec.encode_columns(cols, meta={"op": 1})
    back, meta = codec.decode_columns(data)
    assert meta["op"] == 1
    np.testing.assert_array_equal(back["a"], cols["a"])
    np.testing.assert_array_equal(back["b"], cols["b"])
    assert list(back["s"]) == list(cols["s"])


# ----------------------------------------------------------------------
# WAL
# ----------------------------------------------------------------------

def test_wal_append_replay(tmp_path):
    wal = RegionWal(str(tmp_path / "wal"))
    ids = [wal.append(f"entry{i}".encode()) for i in range(10)]
    assert ids == list(range(10))
    entries = wal.replay()
    assert [e.entry_id for e in entries] == ids
    assert entries[3].payload == b"entry3"
    assert wal.replay(from_id=7) == entries[7:]
    wal.close()
    # reopen recovers next id
    wal2 = RegionWal(str(tmp_path / "wal"))
    assert wal2.next_entry_id == 10
    wal2.close()


def test_wal_torn_tail(tmp_path):
    wal = RegionWal(str(tmp_path / "wal"))
    for i in range(5):
        wal.append(f"e{i}".encode())
    wal.close()
    # corrupt: truncate mid-record
    segs = wal._segments()
    with open(segs[-1], "r+b") as f:
        f.truncate(f.seek(0, 2) - 3)
    wal2 = RegionWal(str(tmp_path / "wal"))
    entries = wal2.replay()
    assert [e.entry_id for e in entries] == [0, 1, 2, 3]
    # appends continue after the torn record...
    assert wal2.append(b"recovered") == 4
    wal2.close()
    # ...and are still readable on the NEXT replay (torn bytes must have
    # been truncated at recovery, not appended past)
    wal3 = RegionWal(str(tmp_path / "wal"))
    entries = wal3.replay()
    assert [e.entry_id for e in entries] == [0, 1, 2, 3, 4]
    assert entries[-1].payload == b"recovered"
    wal3.close()


def test_wal_obsolete(tmp_path):
    wal = RegionWal(str(tmp_path / "wal"), segment_bytes=64)
    for i in range(20):
        wal.append(f"entry-{i:04d}".encode())
    nsegs = len(wal._segments())
    assert nsegs > 1
    wal.obsolete(10)
    assert len(wal._segments()) < nsegs
    remaining = wal.replay()
    assert remaining[-1].entry_id == 19
    # all entries > 10 still present
    ids = [e.entry_id for e in remaining]
    assert set(range(11, 20)) <= set(ids)
    wal.close()


# ----------------------------------------------------------------------
# memtable
# ----------------------------------------------------------------------

def _rows(sid, ts, seq, vals, op=0):
    n = len(sid)
    return ColumnarRows(
        sid=np.asarray(sid, np.int32), ts=np.asarray(ts, np.int64),
        seq=np.asarray(seq, np.uint64), op=np.full(n, op, np.uint8),
        fields={"v": np.asarray(vals, np.float64)},
    )


def test_memtable_scan_window(rng):
    mt = Memtable(["v"], window_ms=1000)
    mt.append(_rows([0, 0, 1], [100, 1500, 2500], [0, 1, 2], [1.0, 2.0, 3.0]))
    assert mt.rows == 3
    assert mt.time_range() == (100, 2500)
    r = mt.scan(ts_min=1000, ts_max=2000)
    assert len(r) == 1 and r.fields["v"][0] == 2.0
    r = mt.scan()
    assert len(r) == 3


# ----------------------------------------------------------------------
# SST
# ----------------------------------------------------------------------

def test_sst_roundtrip_prune(tmp_path, rng):
    store = FsObjectStore(str(tmp_path))
    n = 10_000
    rows = _rows(
        rng.integers(0, 50, n), rng.integers(0, 1_000_000, n),
        np.arange(n), rng.normal(size=n),
    )
    meta = write_sst(store, "sst/a.parquet", "a", rows, row_group_rows=1000)
    assert meta.rows == n
    r = read_sst(store, meta)
    assert len(r) == n
    # sorted by (sid, ts, seq)
    assert np.all(np.diff(r.sid) >= 0)
    # range read returns exactly the matching rows
    r2 = read_sst(store, meta, ts_min=100_000, ts_max=200_000)
    want = ((rows.ts >= 100_000) & (rows.ts <= 200_000)).sum()
    assert len(r2) == want
    assert r2.ts.min() >= 100_000 and r2.ts.max() <= 200_000
    # time range entirely outside -> None
    assert read_sst(store, meta, ts_min=2_000_000) is None
    # sid filter
    r3 = read_sst(store, meta, sids=np.asarray([3, 7]))
    assert set(np.unique(r3.sid)) <= {3, 7}


def test_sst_null_fields(tmp_path):
    store = MemoryObjectStore()
    rows = ColumnarRows(
        sid=np.zeros(4, np.int32), ts=np.arange(4, dtype=np.int64),
        seq=np.arange(4, dtype=np.uint64), op=np.zeros(4, np.uint8),
        fields={"v": np.asarray([1.0, 2.0, 3.0, 4.0])},
        field_valid={"v": np.asarray([True, False, True, False])},
    )
    meta = write_sst(store, "x.parquet", "x", rows)
    r = read_sst(store, meta)
    np.testing.assert_array_equal(r.field_valid["v"],
                                  [True, False, True, False])


# ----------------------------------------------------------------------
# dedup
# ----------------------------------------------------------------------

def test_dedup_last_row():
    rows = _rows([0, 0, 0, 1], [10, 10, 20, 10], [1, 5, 2, 3],
                 [1.0, 99.0, 2.0, 3.0])
    out = dedup_rows(rows)
    assert len(out) == 3
    # (0,10) keeps seq 5 -> 99.0
    assert out.fields["v"][0] == 99.0


def test_dedup_delete_wins():
    rows = _rows([0, 0], [10, 10], [1, 2], [1.0, 0.0])
    rows.op[1] = 1  # delete with higher seq
    out = dedup_rows(rows)
    assert len(out) == 0


def test_dedup_last_non_null():
    rows = ColumnarRows(
        sid=np.zeros(2, np.int32), ts=np.asarray([10, 10], np.int64),
        seq=np.asarray([1, 2], np.uint64), op=np.zeros(2, np.uint8),
        fields={"a": np.asarray([7.0, 0.0]), "b": np.asarray([1.0, 2.0])},
        field_valid={"a": np.asarray([True, False]),
                     "b": np.asarray([True, True])},
    )
    out = dedup_rows(rows, merge_mode="last_non_null")
    assert len(out) == 1
    assert out.fields["a"][0] == 7.0 and out.field_valid["a"][0]
    assert out.fields["b"][0] == 2.0


# ----------------------------------------------------------------------
# region
# ----------------------------------------------------------------------

@pytest.fixture
def region(tmp_path):
    meta = RegionMetadata(
        region_id=1, table="cpu", tag_names=["host", "dc"],
        field_names=["usage", "load"], ts_name="ts",
        options=RegionOptions(wal_sync=False),
    )
    store = FsObjectStore(str(tmp_path / "data"))
    r = Region(meta, store, str(tmp_path / "wal"))
    yield r
    r.close()


def _write_cpu(region, hosts, ts, usage, load=None):
    n = len(ts)
    region.write(
        {"host": np.asarray(hosts, object),
         "dc": np.asarray(["dc1"] * n, object)},
        np.asarray(ts, np.int64),
        {"usage": np.asarray(usage, np.float64),
         "load": np.asarray(load if load is not None else usage, np.float64)},
    )


def test_region_write_scan(region):
    _write_cpu(region, ["a", "b", "a"], [100, 100, 200], [1.0, 2.0, 3.0])
    res = region.scan()
    assert res.num_rows == 3
    r = res.rows
    # series registry maps sids back to tags
    tags = [res.registry.series_tags(int(s)) for s in r.sid]
    hosts = [t["host"] for t in tags]
    assert sorted(zip(hosts, r.ts.tolist())) == [
        ("a", 100), ("a", 200), ("b", 100)
    ]


def test_region_overwrite_and_delete(region):
    _write_cpu(region, ["a"], [100], [1.0])
    _write_cpu(region, ["a"], [100], [9.0])       # overwrite same (series, ts)
    res = region.scan()
    assert res.num_rows == 1 and res.rows.fields["usage"][0] == 9.0
    region.delete({"host": np.asarray(["a"], object),
                   "dc": np.asarray(["dc1"], object)},
                  np.asarray([100], np.int64))
    assert region.scan().num_rows == 0


def test_region_flush_and_replay(tmp_path):
    meta = RegionMetadata(
        region_id=2, table="cpu", tag_names=["host"],
        field_names=["v"], ts_name="ts",
    )
    store = FsObjectStore(str(tmp_path / "data"))
    r = Region(meta, store, str(tmp_path / "wal"))
    r.write({"host": np.asarray(["a", "b"], object)},
            np.asarray([1, 2], np.int64), {"v": np.asarray([1.0, 2.0])})
    r.flush()
    assert len(r.manifest.state.ssts) == 1
    # unflushed rows live only in WAL+memtable
    r.write({"host": np.asarray(["c"], object)},
            np.asarray([3], np.int64), {"v": np.asarray([3.0])})
    sid_c = int(r.scan().rows.sid[-1])
    r.close()

    # reopen: flushed from SST, unflushed replayed from WAL, same sids
    r2 = Region(meta, store, str(tmp_path / "wal"))
    res = r2.scan()
    assert res.num_rows == 3
    assert int(res.rows.sid[-1]) == sid_c
    assert res.registry.series_tags(sid_c) == {"host": "c"}
    np.testing.assert_allclose(np.sort(res.rows.fields["v"]), [1, 2, 3])
    r2.close()


def test_region_scan_prunes_by_time(region):
    _write_cpu(region, ["a"] * 100, list(range(0, 10_000, 100)),
               np.arange(100, dtype=float))
    region.flush()
    res = region.scan(ts_min=5000, ts_max=6000)
    assert res.num_rows == 11
    assert res.rows.ts.min() >= 5000 and res.rows.ts.max() <= 6000


def test_region_truncate(region):
    _write_cpu(region, ["a"], [1], [1.0])
    region.flush()
    _write_cpu(region, ["a"], [2], [2.0])
    region.truncate()
    assert region.scan().num_rows == 0
    # new writes work after truncate
    _write_cpu(region, ["a"], [3], [3.0])
    assert region.scan().num_rows == 1


# ----------------------------------------------------------------------
# compaction
# ----------------------------------------------------------------------

def test_compaction_merges_window(tmp_path):
    meta = RegionMetadata(
        region_id=3, table="t", tag_names=["h"], field_names=["v"],
        ts_name="ts",
        options=RegionOptions(compaction_trigger_files=3,
                              compaction_window_ms=1_000_000),
    )
    store = FsObjectStore(str(tmp_path / "data"))
    r = Region(meta, store, str(tmp_path / "wal"))
    for i in range(3):
        r.write({"h": np.asarray(["x"], object)},
                np.asarray([100 + i], np.int64),
                {"v": np.asarray([float(i)])})
        r.flush()
    assert len(r.manifest.state.ssts) == 3
    assert compact_once(r)
    assert len(r.manifest.state.ssts) == 1
    assert r.manifest.state.ssts[0].level == 1
    res = r.scan()
    assert res.num_rows == 3
    # old files physically deleted
    assert len(store.list(r.prefix + "/sst/")) == 1
    r.close()


def test_compaction_keeps_tombstones(tmp_path):
    """A delete must still shadow a put living in an older level-1 file
    after only level-0 files are compacted."""
    meta = RegionMetadata(
        region_id=4, table="t", tag_names=["h"], field_names=["v"],
        ts_name="ts",
        options=RegionOptions(compaction_trigger_files=3,
                              compaction_window_ms=1_000_000),
    )
    store = FsObjectStore(str(tmp_path / "data"))
    r = Region(meta, store, str(tmp_path / "wal"))
    tags = {"h": np.asarray(["x"], object)}
    # put lands in a level-1 file
    for i in range(3):
        r.write(tags, np.asarray([100], np.int64), {"v": np.asarray([float(i)])})
        r.flush()
    assert compact_once(r)
    assert r.manifest.state.ssts[0].level == 1
    # delete + two filler flushes trigger a second L0-only compaction
    r.delete(tags, np.asarray([100], np.int64))
    r.flush()
    for i in range(2):
        r.write(tags, np.asarray([200 + i], np.int64),
                {"v": np.asarray([9.0])})
        r.flush()
    assert compact_once(r)
    # the deleted row must NOT resurrect
    res = r.scan()
    assert 100 not in res.rows.ts.tolist()
    r.close()


# ----------------------------------------------------------------------
# engine
# ----------------------------------------------------------------------

def test_engine_lifecycle(tmp_path):
    eng = TsdbEngine(EngineConfig(data_root=str(tmp_path),
                                  enable_background=False))
    meta = RegionMetadata(region_id=10, table="t", tag_names=["h"],
                          field_names=["v"], ts_name="ts")
    r = eng.create_region(meta)
    r.write({"h": np.asarray(["a"], object)}, np.asarray([1], np.int64),
            {"v": np.asarray([1.0])})
    eng.maybe_flush()  # below thresholds: no flush
    assert len(r.manifest.state.ssts) == 0
    eng.close_region(10)  # flushes on close
    r2 = eng.open_region(meta)
    assert r2.scan().num_rows == 1
    eng.drop_region(10)
    with pytest.raises(Exception):
        eng.region(10)
    eng.close()


def test_ttl_purges_expired_ssts(tmp_path):
    """TTL drops whole SSTs past the horizon (compaction.purge_expired,
    ref src/mito2/src/compaction.rs get_expired_ssts)."""
    import numpy as np

    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.storage.compaction import purge_expired

    inst = Standalone(str(tmp_path / "ttl"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (ts timestamp time index, v double) "
            "with (ttl = '1h')"
        )
        table = inst.catalog.table("public", "t")
        region = table.regions[0]
        # one old SST, one fresh SST
        table.write({}, np.asarray([1_000], np.int64),
                    {"v": np.asarray([1.0])})
        table.flush()
        now_ms = 10 * 3600_000
        table.write({}, np.asarray([now_ms - 60_000], np.int64),
                    {"v": np.asarray([2.0])})
        table.flush()
        assert len(region.manifest.state.ssts) == 2
        v0 = region.data_version
        assert purge_expired(region, now_ms=now_ms) == 1
        assert len(region.manifest.state.ssts) == 1
        assert region.data_version != v0
        # nothing else expired -> no-op
        assert purge_expired(region, now_ms=now_ms) == 0
        # the fresh row survives on disk (explicit ts_min bypasses the
        # wall-clock TTL read filter for this synthetic timeline)
        res = region.scan(ts_min=0, field_names=["v"])
        assert list(res.rows.fields["v"]) == [2.0]
    finally:
        inst.close()
