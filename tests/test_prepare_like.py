"""SQL PREPARE/EXECUTE/DEALLOCATE, CREATE TABLE LIKE, typed literals,
interval-timestamp coercion (reference: src/operator/src/statement.rs
Prepare/Execute + CreateTableLike; src/common/time interval exprs)."""

import pytest

from greptimedb_tpu.errors import InvalidArgumentError
from greptimedb_tpu.instance import Standalone, substitute_placeholders
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    inst.execute_sql(
        "create table t (ts timestamp time index, host string primary "
        "key, v double)"
    )
    inst.execute_sql(
        "insert into t values (1000,'a',1.0), (2000,'b',2.0)"
    )
    yield inst
    inst.close()


def test_substitute_placeholders():
    assert substitute_placeholders("select ?", [1]) == "select 1"
    assert substitute_placeholders(
        "select * from t where a = ? and b = ?", [1.5, "x'y"]
    ) == "select * from t where a = 1.5 and b = 'x''y'"
    assert substitute_placeholders(
        "select $2, $1", ["a", None]
    ) == "select NULL, 'a'"
    # placeholders inside strings are untouched
    assert substitute_placeholders("select '?', ?", [5]) == "select '?', 5"
    with pytest.raises(InvalidArgumentError):
        substitute_placeholders("select ?, ?", [1])
    with pytest.raises(InvalidArgumentError):
        substitute_placeholders("select $3", [1])


def test_binding_is_injection_safe(inst):
    ctx = QueryContext()
    inst.execute_sql(
        "PREPARE q FROM 'select host from t where host = ?'", ctx
    )
    # a trailing backslash must not escape the closing quote and turn
    # the next fragment into raw SQL
    assert inst.sql("EXECUTE q ('x\\\\')", ctx).rows() == []
    from greptimedb_tpu.instance import (
        format_sql_literal,
        substitute_placeholders,
    )

    bound = substitute_placeholders(
        "select * from t where a = ? and b = ?",
        ["x\\", "', (select 1) --"],
    )
    # both parameters stay inside string literals
    from greptimedb_tpu.sql.lexer import Tok, tokenize

    strings = [t.text for t in tokenize(bound) if t.kind == Tok.STRING]
    assert strings == ["x\\", "', (select 1) --"]
    assert format_sql_literal("C:\\new\\temp") == "'C:\\\\new\\\\temp'"


def test_prepare_execute_deallocate(inst):
    ctx = QueryContext()
    inst.execute_sql(
        "PREPARE q FROM 'select host from t where v > ? order by host'",
        ctx,
    )
    assert inst.sql("EXECUTE q (1.5)", ctx).rows() == [["b"]]
    assert inst.sql("EXECUTE q (0)", ctx).rows() == [["a"], ["b"]]
    inst.execute_sql("PREPARE p AS select v from t where host = $1", ctx)
    assert inst.sql("EXECUTE p ('a')", ctx).rows() == [[1.0]]
    assert inst.sql("EXECUTE p USING 'b'", ctx).rows() == [[2.0]]
    inst.execute_sql("DEALLOCATE PREPARE q", ctx)
    with pytest.raises(InvalidArgumentError):
        inst.sql("EXECUTE q (1)", ctx)
    inst.execute_sql("DEALLOCATE ALL", ctx)
    with pytest.raises(InvalidArgumentError):
        inst.sql("EXECUTE p (1)", ctx)


def test_create_table_like(inst):
    inst.execute_sql("create table t2 like t")
    r = inst.sql("show columns from t2")
    assert list(r.cols[0].values) == ["ts", "host", "v"]
    by_name = dict(zip(r.cols[0].values, r.cols[3].values))
    assert by_name["host"] == "PRI" and by_name["ts"] == "TIME INDEX"
    # independent data
    inst.execute_sql("insert into t2 values (1000,'z',9.0)")
    assert inst.sql("select count(v) from t2").cols[0].values[0] == 1
    assert inst.sql("select count(v) from t").cols[0].values[0] == 2
    # IF NOT EXISTS respected
    inst.execute_sql("create table if not exists t2 like t")


def test_typed_literals_and_interval_coercion(inst):
    r = inst.sql(
        "select v from t where ts > '1970-01-01 00:00:01' - interval '1s'"
    )
    assert sorted(float(x) for x in r.cols[0].values) == [1.0, 2.0]
    r = inst.sql("select timestamp '1970-01-01 00:00:10' + interval '1h'")
    assert r.rows() == [[3610000]]
    r = inst.sql(
        "select v from t where ts >= timestamp '1970-01-01 00:00:02'"
    )
    assert r.rows() == [[2.0]]


def test_prom_status_endpoints(inst):
    import json
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer

    srv = HttpServer(inst, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}/v1/prometheus/api/v1"
        with urllib.request.urlopen(f"{base}/status/buildinfo") as r:
            body = json.load(r)
        assert body["status"] == "success"
        assert body["data"]["version"]
        with urllib.request.urlopen(f"{base}/metadata") as r:
            body = json.load(r)
        assert body["status"] == "success"
        assert "t" in body["data"]
        with urllib.request.urlopen(f"{base}/rules") as r:
            assert json.load(r)["data"] == {"groups": []}
        with urllib.request.urlopen(f"{base}/alertmanagers") as r:
            assert "activeAlertmanagers" in json.load(r)["data"]
    finally:
        srv.stop()
