"""Protocol server tests: HTTP SQL, Prometheus API, InfluxDB line protocol,
Prometheus remote write/read, metrics (the protocol-tests role of
/root/reference/tests-integration/tests/http.rs)."""

import json
import struct
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers import snappy
from greptimedb_tpu.servers.http import HttpServer
from greptimedb_tpu.servers.influx import parse_line, write_lines
from greptimedb_tpu.servers.prom_store import (
    _field_bytes,
    _field_double,
    _field_varint,
    parse_write_request,
)


@pytest.fixture()
def server(tmp_path):
    inst = Standalone(str(tmp_path / "data"))
    srv = HttpServer(inst, port=0).start()
    yield srv
    srv.stop()
    inst.close()


def _req(srv, path, data=None, headers=None, method=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    req = urllib.request.Request(
        url, data=data, headers=headers or {}, method=method
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def _sql(srv, sql, db="public"):
    import urllib.parse

    body = urllib.parse.urlencode({"sql": sql, "db": db}).encode()
    status, data, _ = _req(
        srv, "/v1/sql", body,
        {"Content-Type": "application/x-www-form-urlencoded"}, "POST",
    )
    assert status == 200
    return json.loads(data)


# ----------------------------------------------------------------------
# snappy
# ----------------------------------------------------------------------

def test_snappy_roundtrip():
    data = b"hello world " * 100 + bytes(range(256))
    assert snappy.decompress(snappy.compress(data)) == data


def test_snappy_copy_decode():
    # handcrafted block with a copy: "abcdabcd"
    # varint 8, literal len-4 'abcd' (tag (3)<<2=12), copy1 len4 off4
    block = bytes([8, 12]) + b"abcd" + bytes([0b001, 4])
    assert snappy.decompress(block) == b"abcdabcd"


# ----------------------------------------------------------------------
# line protocol
# ----------------------------------------------------------------------

def test_parse_line_basic():
    m, tags, fields, ts = parse_line(
        "cpu,host=h1,region=us usage_user=10.5,usage_idle=88i 1700000000000"
    )
    assert m == "cpu"
    assert tags == {"host": "h1", "region": "us"}
    assert fields == {"usage_user": 10.5, "usage_idle": 88}
    assert ts == "1700000000000"


def test_parse_line_escapes_and_strings():
    m, tags, fields, ts = parse_line(
        'weird\\ name,tag\\,1=a\\ b msg="hello, \\"world\\"",ok=t'
    )
    assert m == "weird name"
    assert tags == {"tag,1": "a b"}
    assert fields["msg"] == 'hello, "world"'
    assert fields["ok"] is True
    assert ts is None


def test_write_lines_auto_create(tmp_path):
    inst = Standalone(str(tmp_path / "d"))
    n = write_lines(
        inst,
        "cpu,host=h1 usage=10 1700000000000000000\n"
        "cpu,host=h2 usage=20 1700000001000000000\n"
        "mem,host=h1 used=512i 1700000000000000000\n",
        precision="ns",
    )
    assert n == 3
    res = inst.sql("SELECT host, usage FROM cpu ORDER BY host")
    assert res.rows() == [["h1", 10.0], ["h2", 20.0]]
    res = inst.sql("SELECT used FROM mem")
    assert res.rows() == [[512]]
    # widen with a new tag + field
    write_lines(
        inst, "cpu,host=h3,dc=east usage=30,temp=70 1700000002000000000",
        precision="ns",
    )
    res = inst.sql("SELECT host, dc, temp FROM cpu WHERE host = 'h3'")
    assert res.rows() == [["h3", "east", 70.0]]
    # old rows read empty tag, null field
    res = inst.sql("SELECT count(temp) FROM cpu")
    assert res.rows() == [[1]]
    inst.close()


# ----------------------------------------------------------------------
# HTTP SQL
# ----------------------------------------------------------------------

def test_http_sql_roundtrip(server):
    out = _sql(server, "CREATE TABLE t (host STRING, v DOUBLE, "
                       "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")
    assert out["output"][0] == {"affectedrows": 0}
    _sql(server, "INSERT INTO t VALUES ('a', 1.5, 1000), ('b', 2.5, 2000)")
    out = _sql(server, "SELECT host, v FROM t ORDER BY host")
    rec = out["output"][0]["records"]
    assert [c["name"] for c in rec["schema"]["column_schemas"]] == [
        "host", "v",
    ]
    assert [c["data_type"] for c in rec["schema"]["column_schemas"]] == [
        "String", "Float64",
    ]
    assert rec["rows"] == [["a", 1.5], ["b", 2.5]]
    assert "execution_time_ms" in out


def test_http_sql_error(server):
    import urllib.parse, urllib.error

    body = urllib.parse.urlencode({"sql": "SELECT FROM"}).encode()
    try:
        _req(server, "/v1/sql", body,
             {"Content-Type": "application/x-www-form-urlencoded"}, "POST")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "error" in json.loads(e.read())


# ----------------------------------------------------------------------
# InfluxDB over HTTP
# ----------------------------------------------------------------------

def test_http_influx_write_and_query(server):
    body = (
        "cpu,host=h1 usage=42 1700000000000\n"
        "cpu,host=h2 usage=43 1700000000000\n"
    ).encode()
    status, _, _ = _req(
        server, "/v1/influxdb/write?precision=ms", body, {}, "POST"
    )
    assert status == 204
    out = _sql(server, "SELECT host, usage FROM cpu ORDER BY host")
    assert out["output"][0]["records"]["rows"] == [
        ["h1", 42.0], ["h2", 43.0],
    ]


# ----------------------------------------------------------------------
# Prometheus API
# ----------------------------------------------------------------------

def _setup_prom_data(server):
    _sql(server, "CREATE TABLE up (job STRING, greptime_value DOUBLE, "
                 "ts TIMESTAMP TIME INDEX, PRIMARY KEY (job))")
    _sql(server,
         "INSERT INTO up VALUES ('api', 1.0, 1700000000000), "
         "('db', 0.0, 1700000000000), ('api', 1.0, 1700000060000), "
         "('db', 1.0, 1700000060000)")


def test_prom_query_range(server):
    _setup_prom_data(server)
    status, data, _ = _req(
        server,
        "/v1/prometheus/api/v1/query_range?query=up&start=1700000000"
        "&end=1700000060&step=60",
    )
    assert status == 200
    out = json.loads(data)
    assert out["status"] == "success"
    assert out["data"]["resultType"] == "matrix"
    by_job = {
        r["metric"]["job"]: r["values"] for r in out["data"]["result"]
    }
    assert by_job["api"] == [[1700000000.0, "1.0"], [1700000060.0, "1.0"]]


def test_prom_instant_query(server):
    _setup_prom_data(server)
    status, data, _ = _req(
        server,
        "/v1/prometheus/api/v1/query?query=sum(up)&time=1700000060",
    )
    out = json.loads(data)
    assert out["data"]["resultType"] == "vector"
    assert out["data"]["result"][0]["value"][1] == "2.0"


def test_prom_labels_and_values(server):
    _setup_prom_data(server)
    _, data, _ = _req(server, "/v1/prometheus/api/v1/labels")
    labels = json.loads(data)["data"]
    assert "job" in labels and "__name__" in labels
    _, data, _ = _req(
        server, "/v1/prometheus/api/v1/label/__name__/values"
    )
    assert "up" in json.loads(data)["data"]
    _, data, _ = _req(server, "/v1/prometheus/api/v1/label/job/values")
    assert json.loads(data)["data"] == ["api", "db"]


def test_prom_series(server):
    _setup_prom_data(server)
    _, data, _ = _req(
        server,
        "/v1/prometheus/api/v1/series?match[]=up&start=1699999990"
        "&end=1700000070",
    )
    out = json.loads(data)["data"]
    jobs = sorted(s["job"] for s in out)
    assert jobs == ["api", "db"]


# ----------------------------------------------------------------------
# remote write / read
# ----------------------------------------------------------------------

def _make_write_request():
    def label(name, value):
        return _field_bytes(
            1, _field_bytes(1, name.encode()) + _field_bytes(2, value.encode())
        )

    def sample(value, ts):
        return _field_bytes(2, _field_double(1, value) + _field_varint(2, ts))

    def ts_msg(labels, samples):
        return _field_bytes(1, b"".join(labels) + b"".join(samples))

    return (
        ts_msg(
            [label("__name__", "http_total"), label("job", "api")],
            [sample(100.0, 1700000000000), sample(110.0, 1700000015000)],
        )
        + ts_msg(
            [label("__name__", "http_total"), label("job", "web")],
            [sample(200.0, 1700000000000)],
        )
    )


def test_parse_write_request():
    req = _make_write_request()
    series = parse_write_request(req)
    assert len(series) == 2
    labels, samples = series[0]
    assert labels == {"__name__": "http_total", "job": "api"}
    assert samples == [(100.0, 1700000000000), (110.0, 1700000015000)]


def test_remote_write_http(server):
    body = snappy.compress(_make_write_request())
    status, _, _ = _req(
        server, "/v1/prometheus/write", body,
        {"Content-Encoding": "snappy"}, "POST",
    )
    assert status == 204
    out = _sql(server, "SELECT job, greptime_value FROM http_total "
                       "ORDER BY job, ts")
    assert out["output"][0]["records"]["rows"] == [
        ["api", 100.0], ["api", 110.0], ["web", 200.0],
    ]
    # and it is queryable through PromQL
    status, data, _ = _req(
        server,
        "/v1/prometheus/api/v1/query?query=http_total&time=1700000015",
    )
    res = json.loads(data)["data"]["result"]
    assert {r["metric"]["job"] for r in res} == {"api", "web"}


def test_remote_read_http(server):
    body = snappy.compress(_make_write_request())
    _req(server, "/v1/prometheus/write", body,
         {"Content-Encoding": "snappy"}, "POST")
    # ReadRequest: query { start=1, end=17000001000000, matcher __name__ }
    matcher = _field_bytes(3, (
        _field_varint(1, 0) + _field_bytes(2, b"__name__")
        + _field_bytes(3, b"http_total")
    ))
    query = _field_bytes(1, (
        _field_varint(1, 1) + _field_varint(2, 1700000100000) + matcher
    ))
    status, data, headers = _req(
        server, "/v1/prometheus/read", snappy.compress(query), {}, "POST"
    )
    assert status == 200
    resp = snappy.decompress(data)
    # results(1) -> timeseries(1) -> labels(1)/samples(2)
    from greptimedb_tpu.servers.prom_store import _iter_fields

    n_series = 0
    values = []
    for f, w, v in _iter_fields(resp):
        assert f == 1
        for f2, w2, v2 in _iter_fields(v):
            n_series += 1
            for f3, w3, v3 in _iter_fields(v2):
                if f3 == 2:
                    for f4, w4, v4 in _iter_fields(v3):
                        if f4 == 1:
                            values.append(struct.unpack("<d", v4)[0])
    assert n_series == 2
    assert sorted(values) == [100.0, 110.0, 200.0]


# ----------------------------------------------------------------------
# observability endpoints
# ----------------------------------------------------------------------

def test_metrics_endpoint(server):
    _sql(server, "SELECT 1")
    status, data, _ = _req(server, "/metrics")
    assert status == 200
    text = data.decode()
    assert "greptime_servers_http_requests_total" in text


def test_health_and_status(server):
    status, data, _ = _req(server, "/health")
    assert status == 200
    status, data, _ = _req(server, "/status")
    assert json.loads(data)["version"]


def test_influx_ns_precision_exact(tmp_path):
    """ns->ms conversion must be exact integer math: float scaling at
    epoch-scale nanoseconds rounds the input (float64 ULP ~256ns there),
    flipping milliseconds and silently colliding adjacent rows into
    last-write-wins dedup (observed: ~1% row loss on 1ms-spaced data)."""
    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.servers import influx

    inst = Standalone(str(tmp_path / "d"), warm_start=False)
    try:
        inst.sql("CREATE TABLE px (host STRING, v DOUBLE, "
                 "ts TIMESTAMP TIME INDEX, PRIMARY KEY (host))")
        ts0 = 1_700_000_000_000
        n = 500
        body = "\n".join(
            f"px,host=h{i % 7} v={i}.5 {(ts0 + i) * 1_000_000}"
            for i in range(n)
        )
        assert influx.write_lines(inst, body, precision="ns") == n
        r = inst.sql("SELECT count(*), min(ts), max(ts) FROM px")
        row = r.rows()[0]
        assert int(row[0]) == n, f"rows collided: {row[0]}/{n}"
        assert int(row[1]) == ts0 and int(row[2]) == ts0 + n - 1
    finally:
        inst.close()


def test_sql_response_formats(server):
    _sql(server, "CREATE TABLE fmt_t (ts TIMESTAMP TIME INDEX, "
                 "host STRING PRIMARY KEY, v DOUBLE)")
    _sql(server, "INSERT INTO fmt_t VALUES (1000, 'a', 1.5), "
                 "(2000, 'b', NULL)")
    import urllib.parse
    import urllib.request

    def fetch(fmt):
        q = urllib.parse.urlencode({
            "sql": "SELECT host, v FROM fmt_t ORDER BY ts",
            "format": fmt,
        })
        url = f"http://127.0.0.1:{server.port}/v1/sql?{q}"
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.headers.get("Content-Type"), r.read().decode()

    ctype, body = fetch("csv")
    assert ctype.startswith("text/csv")
    assert body.splitlines() == ["host,v", "a,1.5", "b,"]
    ctype, body = fetch("table")
    assert "| host | v    |" in body and "| b    | NULL |" in body
    # unknown format errors
    q = urllib.parse.urlencode({"sql": "SELECT 1", "format": "nope"})
    url = f"http://127.0.0.1:{server.port}/v1/sql?{q}"
    import pytest as _pytest

    with _pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(url, timeout=30)
