"""gtcontract (GT028-GT032): the whole-program wire/config/metric
contract verifier.

Fixture mini-projects live in triple-quoted strings (never in this
module's own AST — the full-package lint harvests tests/ as a
consumer surface, so real `.action(...)` calls or `gtpu_*`-suffixed
string literals here would leak into the live contract model).
"""

from __future__ import annotations

import ast
import io
import json
import os

import pytest

from greptimedb_tpu.tools.lint.baseline import Baseline
from greptimedb_tpu.tools.lint.contracts import (
    CONTRACT_RULE_IDS,
    ContractRule,
    contract_findings,
    extract_model,
)
from greptimedb_tpu.tools.lint.core import all_rules
from greptimedb_tpu.tools.lint.runner import (
    contracts_dump,
    lint_paths,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "greptimedb_tpu")


def _model(src_by_path, readme=None):
    forest = {p: (s, ast.parse(s)) for p, s in src_by_path.items()}
    return extract_model(forest, readme_text=readme)


def _check(src_by_path, select=None, readme=None):
    rules = all_rules()
    if select:
        rules = {k: v for k, v in rules.items() if k in select}
    return contract_findings(_model(src_by_path, readme=readme), rules)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def _messages(findings):
    return "\n".join(f.message for f in findings)


# ----------------------------------------------------------------------
# registration / framework shape
# ----------------------------------------------------------------------

def test_contract_rules_registered_and_cross_file():
    rules = all_rules()
    for rid in CONTRACT_RULE_IDS:
        assert rid in rules
        rule = rules[rid]
        assert isinstance(rule, ContractRule)
        assert rule.description and rule.example_pos and rule.example_neg
        # contract rules are model-checked, not AST-walked: no visitor
        # methods may shadow the per-file dispatch
        assert not [m for m in dir(rule) if m.startswith("visit_")]


# ----------------------------------------------------------------------
# GT028 tickets
# ----------------------------------------------------------------------

_PRODUCER = '''\
def encode(deadline, epoch):
    dl_field = b'' if deadline is None \\
        else b'"deadline_s":%.3f,' % deadline
    ep_field = b'"epoch_ms":%d,' % epoch
    return (b'{"rpc":"partial_sql",' + dl_field + ep_field
            + b'"mode":"plan","plan":null}')
'''

_DECODER = '''\
import re

_DEADLINE_FIELD_RE = re.compile(r'"deadline_s":[0-9.eE+-]+,')
_EPOCH_FIELD_RE = re.compile(r'"epoch_ms":-?\\d+,')

def _decode_ticket(raw, doc):
    return raw

def exec_partial(raw, doc):
    raw = _DEADLINE_FIELD_RE.sub("", raw, count=1)
    raw = _EPOCH_FIELD_RE.sub("", raw, count=1)
    plan = _decode_ticket(raw, doc)
    return plan, (doc.get("deadline_s"), doc.get("epoch_ms"))
'''


def test_gt028_ticket_extraction_two_files():
    model = _model({"a/encode.py": _PRODUCER, "a/decode.py": _DECODER})
    assert model.has_producer_surface and model.has_decode_surface
    assert set(model.ticket_producers) == {"deadline_s", "epoch_ms"}
    assert set(model.ticket_strips) == {"deadline_s", "epoch_ms"}
    assert {"deadline_s", "epoch_ms"} <= model.ticket_reanchors
    # producer sites anchor in the producer module
    assert model.ticket_producers["epoch_ms"][0].path == "a/encode.py"
    assert not _check({"a/encode.py": _PRODUCER,
                       "a/decode.py": _DECODER}, select={"GT028"})


def test_gt028_produced_field_not_stripped():
    decoder = _DECODER.replace(
        "_EPOCH_FIELD_RE = re.compile(r'\"epoch_ms\":-?\\d+,')\n", ""
    ).replace('    raw = _EPOCH_FIELD_RE.sub("", raw, count=1)\n', "")
    fs = _check({"a/encode.py": _PRODUCER, "a/decode.py": decoder},
                select={"GT028"})
    assert _rules_of(fs) == ["GT028"]
    assert "'epoch_ms'" in _messages(fs)
    assert "strip" in _messages(fs)
    # anchored at the producer splice, where the fix starts
    assert fs[0].path == "a/encode.py"


def test_gt028_stripped_but_never_reanchored():
    decoder = _DECODER.replace(', doc.get("epoch_ms")', "")
    fs = _check({"a/encode.py": _PRODUCER, "a/decode.py": decoder},
                select={"GT028"})
    assert len(fs) == 1 and "never read back" in fs[0].message
    assert fs[0].path == "a/decode.py"


def test_gt028_stale_strip_entry():
    producer = _PRODUCER.replace(
        "    ep_field = b'\"epoch_ms\":%d,' % epoch\n", ""
    ).replace(" + ep_field", "")
    fs = _check({"a/encode.py": producer, "a/decode.py": _DECODER},
                select={"GT028"})
    assert len(fs) == 1 and "stale" in fs[0].message


def test_gt028_strip_compiled_but_never_applied():
    decoder = _DECODER.replace(
        '    raw = _EPOCH_FIELD_RE.sub("", raw, count=1)\n', "")
    fs = _check({"a/encode.py": _PRODUCER, "a/decode.py": decoder},
                select={"GT028"})
    assert len(fs) == 1 and "never applied via .sub()" in fs[0].message


def test_gt028_gated_on_both_surfaces():
    # producer alone (no decode module in the forest): no findings,
    # even though nothing is stripped anywhere
    assert not _check({"a/encode.py": _PRODUCER}, select={"GT028"})
    # decoder alone: its strips are not "stale" without a producer
    assert not _check({"a/decode.py": _DECODER}, select={"GT028"})


def test_gt028_seeded_regression_against_real_dataplane():
    """Inject an unstripped volatile field into the REAL fan-out
    encoder and lint it against the REAL decode module: the gate must
    catch the drift. This pins the harvest against the live idiom
    (conditional bytes fragments concatenated into the base literal),
    not just the synthetic fixtures above."""
    dq = os.path.join(PKG, "dist", "dist_query.py")
    mg = os.path.join(PKG, "dist", "merge.py")
    with open(dq, encoding="utf-8") as f:
        dq_src = f.read()
    with open(mg, encoding="utf-8") as f:
        mg_src = f.read()
    needle = "dl_field + tp_field"
    assert needle in dq_src, "fan-out encoder idiom moved; update test"
    seeded = dq_src.replace(
        needle, "dl_field + b'\"epoch_ms\":123,' + tp_field", 1)
    clean = _check({"greptimedb_tpu/dist/dist_query.py": dq_src,
                    "greptimedb_tpu/dist/merge.py": mg_src},
                   select={"GT028"})
    assert not clean, f"live dataplane not clean: {_messages(clean)}"
    fs = _check({"greptimedb_tpu/dist/dist_query.py": seeded,
                 "greptimedb_tpu/dist/merge.py": mg_src},
                select={"GT028"})
    assert len(fs) == 1 and "'epoch_ms'" in fs[0].message
    assert fs[0].path == "greptimedb_tpu/dist/dist_query.py"


# ----------------------------------------------------------------------
# GT029 config knobs
# ----------------------------------------------------------------------

_CONFIG = '''\
DEFAULTS = {
    "retry_budget": 3,
    "server": {"port": 4000, "workers": 8, "tenants": {}},
}
'''


def test_gt029_knob_extraction():
    model = _model({"a/config.py": _CONFIG})
    assert model.has_config_surface
    assert model.knob_defaults["server.port"][0] == "4000"
    assert "retry_budget" in model.knob_defaults
    assert "server" in model.knob_sections
    assert "server.tenants" in model.knob_dynamic


def test_gt029_clean_when_everything_consumed():
    src = _CONFIG + '''
def serve(opts):
    limit = opts.get("server.tenants.alice.rps")
    return opts.get("server.port"), opts.get("server.workers"), \\
        opts.get("retry_budget"), limit
'''
    assert not _check({"a/config.py": src}, select={"GT029"})


def test_gt029_read_but_undeclared():
    src = _CONFIG + '''
def serve(opts):
    return opts.get("server.port"), opts.get("server.backlog"), \\
        opts.get("server.workers"), opts.get("retry_budget")
'''
    fs = _check({"a/config.py": src}, select={"GT029"})
    assert len(fs) == 1
    assert "'server.backlog'" in fs[0].message
    assert "not declared" in fs[0].message


def test_gt029_undeclared_ignores_plain_dict_gets():
    # dotted .get on a non-config namespace ("cache" is no section)
    src = _CONFIG + '''
def serve(opts, cache):
    cache.get("cache.hot.key")
    return opts.get("server.port"), opts.get("server.workers"), \\
        opts.get("retry_budget")
'''
    assert not _check({"a/config.py": src}, select={"GT029"})


def test_gt029_section_never_consulted():
    src = _CONFIG + '''
def serve(opts):
    return opts.get("retry_budget")
'''
    fs = _check({"a/config.py": src}, select={"GT029"})
    assert any("[server]" in f.message
               and "no code path consults" in f.message for f in fs)


def test_gt029_knob_never_read_vs_name_pool():
    # knobs consumed through config-object fields in another module —
    # the name pool must count those as reads (no dotted get anywhere)
    consumer = '''
class ServerCfg:
    def __init__(self, section):
        self.port = section["port"]
        self.workers = section["workers"]

def serve(opts):
    return ServerCfg(opts.section("server")), opts.get("retry_budget")
'''
    assert not _check({"a/config.py": _CONFIG, "a/app.py": consumer},
                      select={"GT029"})
    # a consulted section whose knob names appear NOWHERE: never-read
    # fires per knob (the dynamic "tenants" table stays exempt)
    no_field_reads = '''
def serve(opts):
    opts.section("server")
    return opts.get("retry_budget")
'''
    fs = _check({"a/config.py": _CONFIG, "a/app.py": no_field_reads},
                select={"GT029"})
    flagged = {f.message.split("'")[1] for f in fs}
    assert flagged == {"server.port", "server.workers"}


def test_gt029_undocumented_only_with_readme_in_scope():
    src = _CONFIG + '''
def serve(opts):
    return opts.get("server.port"), opts.get("server.workers"), \\
        opts.get("retry_budget"), opts.get("server.tenants.x.rps")
'''
    # no README in scope (fixtures, lint_source): check skipped
    assert not _check({"a/config.py": src}, select={"GT029"})
    readme = "| `server.port` | 4000 | port |\n retry_budget, tenants"
    fs = _check({"a/config.py": src}, select={"GT029"}, readme=readme)
    assert len(fs) == 1
    assert "'server.workers'" in fs[0].message
    assert "not documented" in fs[0].message


# ----------------------------------------------------------------------
# GT030 error codes
# ----------------------------------------------------------------------

_ERRORS = '''\
class StatusCode:
    RATE_LIMITED = 6001
    QUERY_TIMEOUT = 3002

class RateLimitedError(Exception):
    status_code = StatusCode.RATE_LIMITED

class QueryTimeoutError(Exception):
    status_code = StatusCode.QUERY_TIMEOUT

_CODE_CLASSES = {
    StatusCode.RATE_LIMITED: RateLimitedError,
    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,
}
'''


def test_gt030_error_extraction():
    model = _model({"a/errors.py": _ERRORS})
    assert model.has_error_surface and model.has_code_map
    assert model.status_codes["RATE_LIMITED"][0] == 6001
    assert model.error_classes["RateLimitedError"][0] == "RATE_LIMITED"
    assert model.code_classes["QUERY_TIMEOUT"][0] == "QueryTimeoutError"
    assert not _check({"a/errors.py": _ERRORS}, select={"GT030"})


def test_gt030_duplicate_code_number():
    src = _ERRORS.replace("QUERY_TIMEOUT = 3002", "QUERY_TIMEOUT = 6001")
    fs = _check({"a/errors.py": src}, select={"GT030"})
    assert any("duplicates code number 6001" in f.message for f in fs)


def test_gt030_missing_code_map_representative():
    src = _ERRORS.replace(
        "    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,\n", "")
    fs = _check({"a/errors.py": src}, select={"GT030"})
    assert len(fs) == 1
    assert "QueryTimeoutError" in fs[0].message
    assert "no representative" in fs[0].message


def test_gt030_inconsistent_representative():
    src = _ERRORS.replace(
        "StatusCode.QUERY_TIMEOUT: QueryTimeoutError",
        "StatusCode.QUERY_TIMEOUT: RateLimitedError")
    fs = _check({"a/errors.py": src}, select={"GT030"})
    assert any("re-tags" in f.message for f in fs)


def test_gt030_http_table_dead_row():
    http = '''
table = {
    StatusCode.RATE_LIMITED: 429,
    StatusCode.QUERY_TIMEOUT: 408,
    StatusCode.CANCELLED: 499,
}
'''
    src = _ERRORS.replace("QUERY_TIMEOUT = 3002",
                          "QUERY_TIMEOUT = 3002\n    CANCELLED = 3003")
    fs = _check({"a/errors.py": src, "a/http.py": http},
                select={"GT030"})
    assert len(fs) == 1
    assert "CANCELLED" in fs[0].message
    assert "dead mapping row" in fs[0].message
    # a row for an undefined member is worse: different message
    fs = _check({"a/errors.py": _ERRORS, "a/http.py": http},
                select={"GT030"})
    assert any("not a defined StatusCode member" in f.message
               for f in fs)


def test_gt030_http_check_gated_on_error_surface():
    http = '''
table = {
    StatusCode.RATE_LIMITED: 429,
    StatusCode.QUERY_TIMEOUT: 408,
    StatusCode.CANCELLED: 499,
}
'''
    assert not _check({"a/http.py": http}, select={"GT030"})


# ----------------------------------------------------------------------
# GT031 metric families
# ----------------------------------------------------------------------

_METRICS = '''\
registry.counter("gtpu_rows_total", "rows", ("table",))
registry.histogram("gtpu_scan_seconds", "scan wall", labels=("stage",))
'''


def test_gt031_metric_extraction():
    model = _model({"a/metrics.py": _METRICS})
    regs = model.metric_regs
    assert set(regs) == {"gtpu_" + "rows_total", "gtpu_" + "scan_seconds"}
    kind, labels, _ = regs["gtpu_" + "rows_total"][0]
    assert (kind, labels) == ("counter", ("table",))
    kind, labels, _ = regs["gtpu_" + "scan_seconds"][0]
    assert (kind, labels) == ("histogram", ("stage",))
    # the registration call's own name argument is not a reference
    assert not model.metric_refs
    assert not _check({"a/metrics.py": _METRICS}, select={"GT031"})


def test_gt031_referenced_but_unregistered():
    render = '''
def render(registry):
    return registry.get("gtpu_rows_total"), \\
        registry.get("gtpu_cache_hits_total")
'''
    fs = _check({"a/metrics.py": _METRICS, "a/render.py": render},
                select={"GT031"})
    assert len(fs) == 1
    assert "cache_hits_total" in fs[0].message
    assert "never registered" in fs[0].message


def test_gt031_bare_literal_reference_and_histogram_derived():
    probe = '''
def assert_families(text):
    assert "gtpu_scan_seconds_bucket" in text
    assert "gtpu_scan_seconds_count" in text
    assert "gtpu_rows_total" in text
'''
    # _bucket/_count resolve to the registered base histogram: clean
    assert not _check({"a/metrics.py": _METRICS, "a/probe.py": probe},
                      select={"GT031"})
    # same derived names with no registered base: flagged
    fs = _check({"a/metrics.py": _METRICS.replace("histogram",
                                                  "counter"),
                 "a/probe.py": probe}, select={"GT031"})
    assert len(fs) == 2
    assert "scan_seconds" in _messages(fs)


def test_gt031_contextvar_names_are_not_references():
    src = '''
import contextvars
_SINCE = contextvars.ContextVar("gtpu_since_ms", default=None)
'''
    assert not _check({"a/metrics.py": _METRICS, "a/ctx.py": src},
                      select={"GT031"})


def test_gt031_inconsistent_registrations():
    drift = _METRICS + \
        'other_registry.counter("gtpu_rows_total", "rows", ("db",))\n'
    fs = _check({"a/metrics.py": drift}, select={"GT031"})
    assert len(fs) == 1 and "inconsistent label sets" in fs[0].message
    drift = _METRICS + \
        'other_registry.gauge("gtpu_rows_total", "rows", ("table",))\n'
    fs = _check({"a/metrics.py": drift}, select={"GT031"})
    assert len(fs) == 1 and "inconsistent kinds" in fs[0].message


def test_gt031_gated_on_registration_surface():
    render = '''
def render(registry):
    return registry.get("gtpu_rows_total")
'''
    assert not _check({"a/render.py": render}, select={"GT031"})


# ----------------------------------------------------------------------
# GT032 Flight actions
# ----------------------------------------------------------------------

_CLIENT = '''\
def flush(client):
    return client.action("flush_region", b"{}")

def probe(flight, addr):
    return flight.Action("node_probe", b"{}")

def chained(self, addr):
    return self._pool_for(addr).action("reset_region", b"{}")
'''

_SERVER = '''\
class Server:
    def do_action(self, kind, body):
        if kind == "flush_region":
            return b"ok"
        if kind in ("reset_region", "node_probe"):
            return b"ok"
        raise KeyError(kind)

    def list_actions(self, context):
        return [("flush_region", "flush one region"),
                ("reset_region", "reset one region"),
                ("node_probe", "liveness probe")]
'''


def test_gt032_action_extraction():
    model = _model({"a/client.py": _CLIENT, "a/server.py": _SERVER})
    assert set(model.action_dispatches) == {"flush_region",
                                            "node_probe",
                                            "reset_region"}
    assert set(model.action_handlers) == {"flush_region",
                                          "reset_region", "node_probe"}
    assert set(model.action_advertised) == set(model.action_handlers)
    assert model.has_handler_surface and model.has_advertise_surface
    assert not _check({"a/client.py": _CLIENT, "a/server.py": _SERVER},
                      select={"GT032"})


def test_gt032_dispatch_without_handler():
    server = _SERVER.replace(', "node_probe"', "")
    fs = _check({"a/client.py": _CLIENT, "a/server.py": server},
                select={"GT032"})
    assert any("'node_probe'" in f.message
               and "no do_action handler" in f.message for f in fs)
    assert fs[0].path == "a/client.py"


def test_gt032_handler_without_dispatch():
    client = _CLIENT.replace(
        'def probe(flight, addr):\n'
        '    return flight.Action("node_probe", b"{}")\n', "")
    fs = _check({"a/client.py": client, "a/server.py": _SERVER},
                select={"GT032"})
    assert len(fs) == 1
    assert "dead wire surface" in fs[0].message


def test_gt032_advertisement_drift():
    server = _SERVER.replace(
        '                ("node_probe", "liveness probe")', "").replace(
        '("reset_region", "reset one region"),\n',
        '("reset_region", "reset one region")')
    fs = _check({"a/client.py": _CLIENT, "a/server.py": server},
                select={"GT032"})
    assert any("not advertised" in f.message for f in fs)
    server = _SERVER.replace('        if kind in ("reset_region", '
                             '"node_probe"):\n            return b"ok"'
                             '\n', "")
    fs = _check({"a/client.py": _CLIENT, "a/server.py": server},
                select={"GT032"})
    assert any("advertises" in f.message and "no do_action branch"
               in f.message for f in fs)


def test_gt032_foreign_action_namespaces_ignored():
    # `kind == "flush"` matching in a module WITHOUT a do_action entry
    # point (e.g. a manifest's apply_action) is a different namespace
    manifest = '''
def apply_action(state, kind, doc):
    if kind == "flush":
        return state
    if kind == "edit":
        return doc
    raise ValueError(kind)
'''
    model = _model({"a/client.py": _CLIENT, "a/server.py": _SERVER,
                    "a/manifest.py": manifest})
    assert "flush" not in model.action_handlers
    assert "edit" not in model.action_handlers
    assert not _check({"a/client.py": _CLIENT, "a/server.py": _SERVER,
                       "a/manifest.py": manifest}, select={"GT032"})


def test_gt032_gated_on_counterpart_surface():
    # dispatches alone: no handler surface in the forest, stay silent
    assert not _check({"a/client.py": _CLIENT}, select={"GT032"})
    # handlers alone: no dispatch surface, stay silent
    assert not _check({"a/server.py": _SERVER}, select={"GT032"})


# ----------------------------------------------------------------------
# runner integration: lint_source, suppressions, baseline, dump
# ----------------------------------------------------------------------

def test_lint_source_runs_contract_rules_single_file():
    src = _ERRORS.replace(
        "    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,\n", "")
    active, suppressed = lint_source("greptimedb_tpu/example.py", src,
                                     select={"GT030"})
    assert len(active) == 1 and active[0].rule == "GT030"
    assert not suppressed


def test_contract_finding_suppression_roundtrip():
    src = _ERRORS.replace(
        "class QueryTimeoutError(Exception):",
        "class QueryTimeoutError(Exception):  # gtlint: disable=GT030"
    ).replace(
        "    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,\n", "")
    active, suppressed = lint_source("greptimedb_tpu/example.py", src,
                                     select={"GT030"})
    assert not active
    assert len(suppressed) == 1 and suppressed[0].rule == "GT030"


def test_contract_finding_baseline_roundtrip(tmp_path):
    src = _ERRORS.replace(
        "    StatusCode.QUERY_TIMEOUT: QueryTimeoutError,\n", "")
    findings, _ = lint_source("greptimedb_tpu/example.py", src,
                              select={"GT030"})
    lines = src.splitlines()

    def line_text(path, lineno):
        return lines[lineno - 1].strip()

    base = Baseline.from_findings(findings, line_text)
    path = os.path.join(tmp_path, "baseline.json")
    base.save(path)
    loaded = Baseline.load(path)
    new, old, stale = loaded.split(findings, line_text)
    assert not new and not stale and len(old) == 1
    # fixing the violation turns the entry stale (the file must shrink)
    new, old, stale = loaded.split([], line_text)
    assert not new and not old and len(stale) == 1


def test_lint_paths_aux_harvest_catches_partial_forest(tmp_path):
    """A run over one directory still checks against the WHOLE
    program: the aux harvest pulls in the rest of the package, so a
    fixture producing an unstripped ticket field is caught against the
    real dist/merge.py decode surface."""
    fix = tmp_path / "rogue.py"
    fix.write_text(
        _PRODUCER.replace("epoch_ms", "rogue_ms"), encoding="utf-8")
    res = lint_paths([str(tmp_path)], select={"GT028"})
    assert [f["rule"] for f in res["findings"]] == ["GT028"]
    assert "rogue_ms" in res["findings"][0]["message"]
    # the clean tree has no GT028 debt
    res = lint_paths([os.path.join(PKG, "dist")], select={"GT028"})
    assert res["findings"] == []


def test_changed_mode_skips_contract_pass(tmp_path):
    """--changed (a partial forest) must not run cross-file rules —
    the same rogue producer is silent there and the full gate run is
    what catches it."""
    fix = tmp_path / "rogue.py"
    fix.write_text(
        _PRODUCER.replace("epoch_ms", "rogue_ms"), encoding="utf-8")
    only = {os.path.normpath(str(fix))}
    res = lint_paths([str(tmp_path)], select={"GT028"}, only=only)
    assert res["findings"] == []


def test_marker_free_scan_skips_aux_harvest(tmp_path, monkeypatch):
    """A scanned set with no contract-relevant text cannot contribute
    to the model, so the whole-repo aux harvest is skipped (this is
    what keeps `gtlint <plain fixture dir>` at milliseconds); any
    contract marker in the scan brings the harvest back."""
    from greptimedb_tpu.tools.lint import runner

    calls = []
    monkeypatch.setattr(runner, "_aux_paths",
                        lambda done: calls.append(1) or [])
    (tmp_path / "a.py").write_text("def f():\n    return 1\n",
                                   encoding="utf-8")
    res = runner.lint_paths([str(tmp_path)])
    assert res["clean"] and not calls
    (tmp_path / "b.py").write_text(
        "def g(opts):\n    return opts" + ".get('http.addr')\n",
        encoding="utf-8")
    runner.lint_paths([str(tmp_path)])
    assert calls


def test_partial_model_cache_invalidates_on_text_change():
    """extract_model memoizes per-file partials by (path, text): the
    same path re-extracted with different text must yield the new
    file's model, not the cached one."""
    src1 = "class StatusCode:\n    ALPHA = 9101\n"
    src2 = "class StatusCode:\n    BETA = 9102\n"
    m1 = _model({"e.py": src1})
    assert "ALPHA" in m1.status_codes
    m2 = _model({"e.py": src2})
    assert "BETA" in m2.status_codes
    assert "ALPHA" not in m2.status_codes
    # unchanged text hits the cache and still merges fresh containers
    m3 = _model({"e.py": src2})
    assert m3.status_codes["BETA"][0] == 9102


def test_contracts_dump_shape_and_stability():
    out1, out2 = io.StringIO(), io.StringIO()
    assert contracts_dump([PKG], out=out1) == 0
    assert contracts_dump([PKG], out=out2) == 0
    assert out1.getvalue() == out2.getvalue()  # stable key order
    doc = json.loads(out1.getvalue())
    assert set(doc) == {"tickets", "actions", "errors", "knobs",
                        "metrics"}
    # spot-check the live surfaces the five rules verify
    assert "deadline_s" in doc["tickets"]["strips"]
    assert "deadline_s" in doc["tickets"]["producers"]
    assert "flush_region" in doc["actions"]["handlers"]
    assert "flush_region" in doc["actions"]["advertised"]
    assert "RATE_LIMITED" in doc["errors"]["codes"]
    assert "http.addr" in doc["knobs"]["declared"]
    assert any(k.endswith("requests_total")
               for k in doc["metrics"]["registered"])


def test_model_doc_json_round_trip():
    model = _model({"a/client.py": _CLIENT, "a/server.py": _SERVER,
                    "a/errors.py": _ERRORS, "a/config.py": _CONFIG,
                    "a/metrics.py": _METRICS})
    doc = model.to_doc()
    # every site renders as {"path", "line"} and the doc is pure JSON
    again = json.loads(json.dumps(doc, sort_keys=True))
    assert again == json.loads(json.dumps(doc, sort_keys=True))
    site = doc["actions"]["handlers"]["flush_region"][0]
    assert set(site) == {"path", "line"}


@pytest.mark.parametrize("rid", CONTRACT_RULE_IDS)
def test_examples_are_self_contained_mini_projects(rid):
    """Each contract rule's examples carry BOTH sides of their
    contract in one module, so the shared explain meta-test (which
    lints them through lint_source) exercises the cross-file logic."""
    rule = all_rules()[rid]
    pos, _ = lint_source("greptimedb_tpu/example.py", rule.example_pos,
                         select={rid})
    assert [f.rule for f in pos] == [rid], (
        f"{rid} example_pos must fire exactly once: "
        f"{[f.message for f in pos]}")
    neg, _ = lint_source("greptimedb_tpu/example.py", rule.example_neg,
                         select={rid})
    assert not neg, f"{rid} example_neg must stay clean"
