"""Segment (group-by) kernels vs numpy references."""

import numpy as np
import jax.numpy as jnp
import pytest

from greptimedb_tpu.ops import segment as S


@pytest.fixture
def data(rng):
    n, g = 1000, 17
    seg = rng.integers(0, g, n).astype(np.int32)
    vals = rng.normal(size=n).astype(np.float64)
    mask = rng.random(n) > 0.1
    return seg, vals, mask, g


def ref_agg(seg, vals, mask, g, fn, empty=0.0):
    out = np.full(g, empty, dtype=np.float64)
    for i in range(g):
        sel = (seg == i) & mask
        if sel.any():
            out[i] = fn(vals[sel])
    return out


def test_seg_sum(data):
    seg, vals, mask, g = data
    got = np.asarray(S.seg_sum(jnp.array(vals), jnp.array(seg), jnp.array(mask), g))
    np.testing.assert_allclose(got, ref_agg(seg, vals, mask, g, np.sum), rtol=1e-12)


def test_seg_count(data):
    seg, vals, mask, g = data
    got = np.asarray(S.seg_count(jnp.array(seg), jnp.array(mask), g))
    want = np.array([((seg == i) & mask).sum() for i in range(g)])
    np.testing.assert_array_equal(got, want)


def test_seg_min_max(data):
    seg, vals, mask, g = data
    gmin = np.asarray(S.seg_min(jnp.array(vals), jnp.array(seg), jnp.array(mask), g))
    gmax = np.asarray(S.seg_max(jnp.array(vals), jnp.array(seg), jnp.array(mask), g))
    np.testing.assert_allclose(
        gmin, ref_agg(seg, vals, mask, g, np.min, empty=np.inf), rtol=1e-12
    )
    np.testing.assert_allclose(
        gmax, ref_agg(seg, vals, mask, g, np.max, empty=-np.inf), rtol=1e-12
    )


def test_seg_mean_var(data):
    seg, vals, mask, g = data
    mean, cnt = S.seg_mean(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
    want = ref_agg(seg, vals, mask, g, np.mean)
    present = np.asarray(cnt) > 0
    np.testing.assert_allclose(np.asarray(mean)[present], want[present], rtol=1e-10)

    var, _ = S.seg_var(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
    wantv = ref_agg(seg, vals, mask, g, lambda x: np.var(x))
    np.testing.assert_allclose(np.asarray(var)[present], wantv[present], rtol=1e-8)


def test_seg_last_first(data):
    seg, vals, mask, g = data
    last, lp = S.seg_last(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
    first, fp = S.seg_last(
        jnp.array(vals), jnp.array(seg), jnp.array(mask), g, take_first=True
    )
    for i in range(g):
        idx = np.nonzero((seg == i) & mask)[0]
        if len(idx):
            assert lp[i] and fp[i]
            assert last[i] == vals[idx[-1]]
            assert first[i] == vals[idx[0]]
        else:
            assert not lp[i] and not fp[i]


def test_seg_argmax(data):
    seg, vals, mask, g = data
    am = np.asarray(
        S.seg_argmax(jnp.array(vals), jnp.array(seg), jnp.array(mask), g)
    )
    for i in range(g):
        idx = np.nonzero((seg == i) & mask)[0]
        if len(idx):
            assert vals[am[i]] == vals[idx].max()
        else:
            assert am[i] == -1


def test_combine_split_codes():
    c1 = jnp.array([0, 1, 2, 1], dtype=jnp.int32)
    c2 = jnp.array([3, 0, 2, 2], dtype=jnp.int32)
    code, total = S.combine_codes([c1, c2], [3, 4])
    assert total == 12
    np.testing.assert_array_equal(np.asarray(code), [3, 4, 10, 6])
    back = S.split_codes(np.asarray(code), [3, 4])
    np.testing.assert_array_equal(back[0], np.asarray(c1))
    np.testing.assert_array_equal(back[1], np.asarray(c2))


def test_sort_groups(rng):
    n = 500
    a = rng.integers(0, 5, n).astype(np.int32)
    b = rng.integers(0, 7, n).astype(np.int32)
    mask = rng.random(n) > 0.2
    order, seg_ids, starts, ng = S.sort_groups([jnp.array(a), jnp.array(b)],
                                               jnp.array(mask))
    order, seg_ids, starts = map(np.asarray, (order, seg_ids, starts))
    ng = int(ng)
    want_groups = {(int(x), int(y)) for x, y in zip(a[mask], b[mask])}
    assert ng == len(want_groups)
    # each valid sorted row's (a,b) must be constant within a segment
    sa, sb, sm = a[order], b[order], mask[order]
    seen = {}
    for i in range(n):
        if not sm[i]:
            continue
        key = seg_ids[i]
        if key in seen:
            assert seen[key] == (sa[i], sb[i])
        else:
            seen[key] = (sa[i], sb[i])
    assert len(seen) == ng
    # aggregate through the sorted segmentation equals a pandas-style groupby
    vals = rng.normal(size=n)
    sv = jnp.array(vals[order])
    agg = np.asarray(S.seg_sum(sv, jnp.array(seg_ids), jnp.array(sm), n))
    got = {seen[k]: agg[k] for k in seen}
    for key, total in got.items():
        sel = (a == key[0]) & (b == key[1]) & mask
        np.testing.assert_allclose(total, vals[sel].sum(), rtol=1e-12)
