"""Metasrv HA: lease election over the CAS kv (VERDICT missing #9)."""

import json
import time

from greptimedb_tpu.meta.election import Election
from greptimedb_tpu.meta.kv import FsKv, MemoryKv


def test_single_candidate_wins_and_renews():
    kv = MemoryKv()
    e = Election(kv, "a", lease_s=1.0)
    assert e.step(now=100.0)
    assert e.is_leader
    assert e.leader() == ("a", 101.0)
    # renewal extends the lease
    assert e.step(now=100.5)
    assert e.leader() == ("a", 101.5)


def test_second_candidate_waits_then_takes_over():
    kv = MemoryKv()
    a = Election(kv, "a", lease_s=1.0)
    b = Election(kv, "b", lease_s=1.0)
    assert a.step(now=100.0)
    assert not b.step(now=100.1)      # lease held
    assert not b.is_leader
    # a stops renewing; past expiry b steals
    assert b.step(now=101.5)
    assert b.is_leader
    # a's next renewal must FAIL (its bytes were replaced)
    assert not a.step(now=101.6)
    assert not a.is_leader


def test_resign_hands_over_immediately():
    kv = MemoryKv()
    changes = []
    a = Election(kv, "a", lease_s=30.0,
                 on_change=lambda lead: changes.append(("a", lead)))
    b = Election(kv, "b", lease_s=30.0,
                 on_change=lambda lead: changes.append(("b", lead)))
    assert a.step(now=100.0)
    a.resign()
    assert not a.is_leader
    assert b.step(now=100.1)          # no 30s wait after resign
    assert changes == [("a", True), ("a", False), ("b", True)]


def test_no_split_brain_across_fskv_instances(tmp_path):
    """Two FsKv views of ONE file (two processes in real life) must not
    both win: CAS revalidates against the file under an OS lock."""
    path = str(tmp_path / "kv.json")
    a = Election(FsKv(path), "a", lease_s=30.0)
    b = Election(FsKv(path), "b", lease_s=30.0)
    assert a.step(now=100.0)
    assert not b.step(now=100.1), "split brain: both candidates lead"
    assert a.is_leader and not b.is_leader
    # and the loser observes the true leader through its own view
    assert b.leader()[0] == "a"


def test_corrupt_leader_key_is_repaired():
    kv = MemoryKv()
    kv.put("__meta/election/leader", b"not-json")
    e = Election(kv, "a", lease_s=1.0)
    assert e.step(now=100.0), "corrupt key must be reclaimable"
    assert e.leader()[0] == "a"


def test_election_durable_across_kv_reload(tmp_path):
    path = str(tmp_path / "kv.json")
    kv1 = FsKv(path)
    a = Election(kv1, "a", lease_s=30.0)
    assert a.step(now=100.0)
    # a different process view of the same kv sees the same leader
    kv2 = FsKv(path)
    b = Election(kv2, "b", lease_s=30.0)
    assert not b.step(now=100.1)
    assert b.leader()[0] == "a"


def test_fskv_ephemeral_lease_never_rewrites_durable_file(tmp_path):
    """durable=False commits (election leases) go to the `.eph`
    sidecar: the fsync'd durable file is never replaced by an
    un-fsynced copy, so a power loss mid-lease-renewal can lose at
    most the lease — never routes/metadata. (The un-fsynced whole-file
    rewrite was the load-dependent DROP-timeout root cause's fix, and
    this pins that the fix can't cost durable state.)"""
    import os

    path = str(tmp_path / "kv.json")
    kv = FsKv(path)
    kv.put("route/1", b"node-a")          # durable state
    durable_stamp = os.stat(path).st_mtime_ns
    assert kv.compare_and_put("lease", None, b"me", durable=False)
    # the durable file is untouched; the lease lives in the sidecar
    assert os.stat(path).st_mtime_ns == durable_stamp
    assert os.path.exists(path + ".eph")
    # both stores are visible, merged, to a fresh process view
    kv2 = FsKv(path)
    assert kv2.get("route/1") == b"node-a"
    assert kv2.get("lease") == b"me"
    assert dict(kv2.range("")) == {"route/1": b"node-a",
                                   "lease": b"me"}
    # CAS semantics hold across the two stores
    assert not kv2.compare_and_put("lease", b"stale", b"you",
                                   durable=False)
    assert kv2.compare_and_put("lease", b"me", b"you", durable=False)
    assert kv.get("lease") == b"you"      # first view reloads
    # a durable batch write supersedes an ephemeral shadow like put()
    kv2.put_many([("lease", b"durable-now"), ("route/2", b"node-b")])
    assert kv.get("lease") == b"durable-now"
    assert not json.load(open(path + ".eph"))
    # losing the sidecar (the power-loss case) loses ONLY the lease
    kv2.delete("lease")
    assert kv.get("route/1") == b"node-a"
    assert kv.get("lease") is None


def test_metasrv_server_election_and_failover():
    from greptimedb_tpu.servers.meta_http import MetasrvServer

    s1 = MetasrvServer(port=0, election_lease_s=0.6).start()
    # same kv object BEFORE starting: two metasrvs share the backend
    s2 = MetasrvServer(port=0, election_lease_s=0.6)
    s2.kv = s1.kv
    s2.election.kv = s1.kv
    s2.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not s1.election.is_leader:
            time.sleep(0.05)
        assert s1.election.is_leader
        assert not s2.election.is_leader
        # leader dies; follower takes over within ~one lease
        s1.election.stop(resign=True)
        deadline = time.time() + 5
        while time.time() < deadline and not s2.election.is_leader:
            time.sleep(0.05)
        assert s2.election.is_leader
    finally:
        s1.close()
        s2.close()
