"""Cluster control-plane tests: kv, procedures, phi-accrual detection,
region placement, migration, failover (the role of
/root/reference/tests-integration/src/cluster.rs +
tests/region_migration.rs)."""

import numpy as np
import pytest

from greptimedb_tpu.cluster import Cluster
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.meta.failure_detector import PhiAccrualFailureDetector
from greptimedb_tpu.meta.kv import FsKv, MemoryKv
from greptimedb_tpu.meta.procedure import Procedure, ProcedureManager, Status
from greptimedb_tpu.query.executor import QueryEngine
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.sql.parser import parse_sql


def _schema():
    return Schema([
        ColumnSchema("host", ConcreteDataType.string(), SemanticType.TAG,
                     nullable=False),
        ColumnSchema("v", ConcreteDataType.float64(), SemanticType.FIELD),
        ColumnSchema("ts", ConcreteDataType.timestamp_millisecond(),
                     SemanticType.TIMESTAMP, nullable=False),
    ])


def _write_rows(table, n=100, hosts=8):
    tags = {"host": np.asarray([f"h{i % hosts}" for i in range(n)], object)}
    ts = (1_700_000_000_000 + np.arange(n) * 1000).astype(np.int64)
    table.write(tags, ts, {"v": np.arange(n, dtype=np.float64)})


def _count_sum(table):
    stmt = parse_sql("SELECT count(*), sum(v) FROM t")[0]
    plan = plan_select(stmt, ts_name="ts", tag_names=["host"],
                       all_columns=["host", "v", "ts"])
    res = QueryEngine().execute(plan, table)
    return res.rows()[0]


# ----------------------------------------------------------------------
# kv + procedures
# ----------------------------------------------------------------------

def test_fskv_durability(tmp_path):
    path = str(tmp_path / "kv.json")
    kv = FsKv(path)
    kv.put("a", b"1")
    kv.put_json("b", {"x": 2})
    assert kv.compare_and_put("a", b"1", b"2")
    assert not kv.compare_and_put("a", b"1", b"3")
    kv2 = FsKv(path)
    assert kv2.get("a") == b"2"
    assert kv2.get_json("b") == {"x": 2}
    assert [k for k, _ in kv2.range("")] == ["a", "b"]


class _StepProc(Procedure):
    type_name = "Step"

    def __init__(self, steps=3, done=0, fail_at=None):
        self.steps = steps
        self.done_steps = done
        self.fail_at = fail_at
        self.rolled_back = False

    def execute(self, ctx) -> Status:
        if self.fail_at is not None and self.done_steps == self.fail_at:
            raise RuntimeError("injected failure")
        self.done_steps += 1
        if self.done_steps >= self.steps:
            return Status.done(self.done_steps)
        return Status.executing()

    def dump(self):
        return {"steps": self.steps, "done": self.done_steps}

    def rollback(self, ctx):
        self.rolled_back = True

    @classmethod
    def restore(cls, data):
        return cls(steps=data["steps"], done=data["done"])


def test_procedure_success_and_failure():
    kv = MemoryKv()
    mgr = ProcedureManager(kv, max_retries=1, retry_delay_s=0.01)
    meta = mgr.submit_and_wait(_StepProc(3))
    assert meta.state == "done" and meta.output == 3
    assert kv.range("__procedure/") == []  # cleaned up

    proc = _StepProc(3, fail_at=1)
    meta = mgr.submit_and_wait(proc)
    assert meta.state == "rolled_back"
    assert proc.rolled_back


def test_procedure_crash_recovery():
    kv = MemoryKv()
    mgr = ProcedureManager(kv)
    mgr.register_loader("Step", _StepProc)
    # simulate a crash mid-procedure: persist state manually
    kv.put_json("__procedure/abc", {
        "type_name": "Step", "state": "running",
        "data": {"steps": 3, "done": 1},
    })
    resumed = mgr.recover()
    assert resumed == ["abc"]
    meta = mgr.wait("abc")
    assert meta.state == "done" and meta.output == 3


# ----------------------------------------------------------------------
# phi-accrual detector
# ----------------------------------------------------------------------

def test_phi_detector_basics():
    det = PhiAccrualFailureDetector(acceptable_heartbeat_pause_ms=0.0)
    t = 0.0
    for _ in range(20):
        det.heartbeat(t)
        t += 1000.0
    # at the expected next-arrival time: healthy (phi ~ 0.3)
    assert det.phi(t) < 1.0
    assert det.is_available(t)
    # long silence: suspect (zero-variance intervals floor sigma at 100ms,
    # so even 2s of silence is far outside the model)
    assert det.phi(t + 60_000) > det.threshold
    assert not det.is_available(t + 60_000)


# ----------------------------------------------------------------------
# cluster
# ----------------------------------------------------------------------

def test_cluster_create_write_query(tmp_path):
    c = Cluster(str(tmp_path / "c"), n_datanodes=3)
    table = c.create_table("public", "t", _schema(), num_regions=3)
    dist = c.region_distribution()
    assert sum(len(v) for v in dist.values()) == 3
    # regions spread across nodes (round robin over 3 nodes)
    assert all(len(v) == 1 for v in dist.values())
    _write_rows(table, 100)
    cnt, s = _count_sum(c.table("public", "t"))
    assert cnt == 100 and s == float(sum(range(100)))
    c.shutdown()


def test_cluster_restart_recovers(tmp_path):
    root = str(tmp_path / "c")
    c = Cluster(root, n_datanodes=2)
    table = c.create_table("public", "t", _schema(), num_regions=2)
    _write_rows(table, 50)
    c.shutdown()

    c2 = Cluster(root, n_datanodes=2)
    cnt, s = _count_sum(c2.table("public", "t"))
    assert cnt == 50 and s == float(sum(range(50)))
    c2.shutdown()


def test_manual_region_migration(tmp_path):
    c = Cluster(str(tmp_path / "c"), n_datanodes=2)
    table = c.create_table("public", "t", _schema(), num_regions=1)
    _write_rows(table, 40)
    rid = table.info.region_ids()[0]
    src = c.metasrv.route_of(rid)
    dst = 1 - src
    c.metasrv.migrate_region(rid, dst)
    assert c.metasrv.route_of(rid) == dst
    # data fully readable from the new node (flushed by downgrade)
    cnt, s = _count_sum(c.table("public", "t"))
    assert cnt == 40 and s == float(sum(range(40)))
    # old node no longer hosts it
    assert not c.datanodes[src].has_region(rid)
    c.shutdown()


def test_failover_after_crash(tmp_path):
    c = Cluster(str(tmp_path / "c"), n_datanodes=3,
                phi_threshold=3.0)
    table = c.create_table("public", "t", _schema(), num_regions=3)
    _write_rows(table, 90)
    # flush so the shared store has the data (local-WAL deployment)
    for r in table.regions:
        r.flush()

    t0 = 1_000_000.0
    for i in range(10):
        c.heartbeat_all(t0 + i * 1000)
    victim = c.metasrv.route_of(table.info.region_ids()[0])
    c.datanodes[victim].crash()
    # victim misses heartbeats; others stay healthy right up to the tick
    for i in range(10, 22):
        c.heartbeat_all(t0 + i * 1000)
    procs = c.supervise(t0 + 22_000)
    assert procs, "failover should trigger"
    for pid in procs:
        meta = c.metasrv.procedures.wait(pid)
        assert meta.state == "done"
    # all routes now avoid the dead node
    for rid in table.info.region_ids():
        assert c.metasrv.route_of(rid) != victim
    cnt, s = _count_sum(c.table("public", "t"))
    assert cnt == 90 and s == float(sum(range(90)))
    c.shutdown()


def test_failover_with_shared_wal_keeps_unflushed(tmp_path):
    c = Cluster(str(tmp_path / "c"), n_datanodes=2,
                phi_threshold=3.0, shared_wal=True)
    table = c.create_table("public", "t", _schema(), num_regions=1)
    _write_rows(table, 25)  # NOT flushed: lives in WAL + memtable only

    t0 = 1_000_000.0
    for i in range(10):
        c.heartbeat_all(t0 + i * 1000)
    rid = table.info.region_ids()[0]
    victim = c.metasrv.route_of(rid)
    c.datanodes[victim].crash()
    for i in range(10, 22):
        c.heartbeat_all(t0 + i * 1000)
    procs = c.supervise(t0 + 22_000)
    for pid in procs:
        assert c.metasrv.procedures.wait(pid).state == "done"
    # shared WAL replays the victim's unflushed rows on the survivor
    cnt, s = _count_sum(c.table("public", "t"))
    assert cnt == 25 and s == float(sum(range(25)))
    c.shutdown()


def test_load_based_selector(tmp_path):
    c = Cluster(str(tmp_path / "c"), n_datanodes=2, selector="load_based")
    t1 = c.create_table("public", "a", _schema(), num_regions=2)
    _write_rows(t1, 100)
    c.heartbeat_all()
    t2 = c.create_table("public", "b", _schema(), num_regions=2)
    dist = c.region_distribution()
    # both nodes host two regions each (placement balanced)
    assert sorted(len(v) for v in dist.values()) == [2, 2]
    c.shutdown()
