"""ADMIN maintenance functions + session SET/SHOW statements.

Reference surface: src/sql/src/statements/admin.rs (ADMIN func calls),
src/operator/src/statement/set.rs (SET), the MySQL-compat SHOW family
served by the frontend (src/servers/src/mysql/federated.rs).
"""

import numpy as np
import pytest

from greptimedb_tpu.errors import UnsupportedError
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path), prefer_device=False, warm_start=False)
    inst.execute_sql(
        "create table cpu (ts timestamp time index, "
        "host string primary key, usage double)"
    )
    hosts = np.asarray(["a", "b", "c", "a"], object)
    ts = np.asarray([1000, 1000, 2000, 3000], np.int64)
    inst.catalog.table("public", "cpu").write(
        {"host": hosts}, ts, {"usage": np.asarray([1.0, 2.0, 3.0, 4.0])}
    )
    yield inst
    inst.close()


def test_admin_flush_table(inst):
    r = inst.sql("ADMIN flush_table('cpu')")
    assert r.names[0] == "ADMIN flush_table('cpu')"
    assert r.cols[0].values[0] == 1  # one region had rows to flush
    # flushing again is a no-op
    r = inst.sql("ADMIN flush_table('cpu')")
    assert r.cols[0].values[0] == 0


def test_admin_flush_region_and_compact(inst):
    table = inst.catalog.table("public", "cpu")
    rid = table.regions[0].meta.region_id
    r = inst.sql(f"ADMIN flush_region({rid})")
    assert r.cols[0].values[0] == 1
    # two SSTs -> compaction merges them
    table.write(
        {"host": np.asarray(["z"], object)},
        np.asarray([5000], np.int64), {"usage": np.asarray([9.0])},
    )
    inst.sql(f"ADMIN flush_region({rid})")
    inst.sql(f"ADMIN compact_region({rid})")
    res = inst.sql("select count(usage) from cpu")
    assert res.cols[0].values[0] == 5


def test_admin_migrate_region_requires_metasrv(inst):
    with pytest.raises(UnsupportedError):
        inst.sql("ADMIN migrate_region(1, 2)")


def test_admin_unknown_function(inst):
    with pytest.raises(UnsupportedError):
        inst.sql("ADMIN frobnicate()")


def test_set_and_show_variables(inst):
    ctx = QueryContext()
    inst.execute_sql("SET time_zone = '+08:00'", ctx)
    assert ctx.timezone == "+08:00"
    r = inst.sql("SHOW VARIABLES LIKE 'time_zone'", ctx)
    assert list(r.cols[0].values) == ["time_zone"]
    assert list(r.cols[1].values) == ["+08:00"]
    inst.execute_sql("SET max_execution_time = 1000", ctx)
    r = inst.sql("SHOW VARIABLES LIKE 'max_execution_time'", ctx)
    assert list(r.cols[1].values) == ["1000"]
    # unfiltered listing includes server defaults
    r = inst.sql("SHOW VARIABLES", ctx)
    names = list(r.cols[0].values)
    assert "sql_mode" in names and "version" in names
    # postgres-style SET TIME ZONE
    inst.execute_sql("SET TIME ZONE 'UTC'", ctx)
    assert ctx.timezone == "UTC"


def test_show_columns_and_index(inst):
    r = inst.sql("SHOW COLUMNS FROM cpu")
    by_name = dict(zip(r.cols[0].values, r.cols[3].values))
    assert by_name["ts"] == "TIME INDEX"
    assert by_name["host"] == "PRI"
    assert by_name["usage"] == ""
    r = inst.sql("SHOW FULL COLUMNS FROM cpu")
    assert "Semantic Type" in r.names
    r = inst.sql("SHOW INDEX FROM cpu")
    assert "host" in list(r.cols[3].values)
    assert "ts" in list(r.cols[3].values)


def test_show_status_charset_collation_processlist(inst):
    assert inst.sql("SHOW STATUS").num_rows == 1
    assert inst.sql("SHOW CHARSET").cols[0].values[0] == "utf8mb4"
    assert inst.sql("SHOW COLLATION").cols[0].values[0] == "utf8mb4_bin"
    # the processlist contains the SHOW PROCESSLIST statement itself
    r = inst.sql("SHOW PROCESSLIST")
    assert r.num_rows >= 1
    assert "State" in r.names
    assert "ShowProcesslist" in list(r.column("Info").values)


def test_admin_kill_nonexistent(inst):
    r = inst.sql("ADMIN kill('99999')")
    assert r.cols[0].values[0] == 0
    r = inst.sql("KILL QUERY 99999")
    assert r.cols[0].values[0] == 0


def test_admin_missing_arg(inst):
    from greptimedb_tpu.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError):
        inst.sql("ADMIN flush_table()")


def test_set_names_and_multi_assignment(inst):
    ctx = QueryContext()
    # bare-identifier values (connector handshake probes)
    inst.execute_sql("SET NAMES utf8mb4", ctx)
    assert ctx.variables["names"] == "utf8mb4"
    inst.execute_sql("SET autocommit = 1, sql_mode = ANSI", ctx)
    assert ctx.variables["autocommit"] == "1"
    assert ctx.variables["sql_mode"] == "ANSI"


def test_set_connector_handshake_forms(inst):
    ctx = QueryContext()
    inst.execute_sql("SET NAMES utf8mb4 COLLATE utf8mb4_general_ci", ctx)
    assert ctx.variables["names"] == "utf8mb4"
    assert ctx.variables["collation_connection"] == "utf8mb4_general_ci"
    inst.execute_sql(
        "SET SESSION TRANSACTION ISOLATION LEVEL READ COMMITTED", ctx
    )
    assert ctx.variables["transaction_isolation"] == "READ-COMMITTED"
    inst.execute_sql("SET TRANSACTION READ ONLY", ctx)
    assert ctx.variables["transaction_read_only"] == "ON"
    inst.execute_sql(
        "SET TRANSACTION ISOLATION LEVEL REPEATABLE READ", ctx
    )
    assert ctx.variables["transaction_isolation"] == "REPEATABLE-READ"
    # postgres juxtaposed form (no comma)
    inst.execute_sql(
        "SET TRANSACTION ISOLATION LEVEL SERIALIZABLE READ ONLY", ctx
    )
    assert ctx.variables["transaction_isolation"] == "SERIALIZABLE"
    assert ctx.variables["transaction_read_only"] == "ON"


def test_show_columns_qualified(inst):
    r = inst.sql("SHOW COLUMNS FROM public.cpu")
    assert "host" in list(r.cols[0].values)
    # LIKE metacharacters are literal except % and _
    r = inst.sql("SHOW COLUMNS FROM cpu LIKE 'usage'")
    assert list(r.cols[0].values) == ["usage"]
    r = inst.sql("SHOW COLUMNS FROM cpu LIKE 'h%'")
    assert list(r.cols[0].values) == ["host"]


def test_kill_running_query_cancels_at_checkpoint(inst):
    """A kill lands mid-statement and the victim raises at its next
    per-region scan checkpoint."""
    import threading
    import time

    from greptimedb_tpu import cancellation
    from greptimedb_tpu.errors import ExecutionError

    started = threading.Event()
    results = {}

    orig_checkpoint = cancellation.checkpoint

    def run_victim():
        ctx = QueryContext()
        try:
            # monkeypatched checkpoint below blocks until the kill lands
            results["r"] = inst.sql("select count(usage) from cpu", ctx)
        except ExecutionError as e:
            results["err"] = str(e)

    def slow_checkpoint():
        started.set()
        time.sleep(0.3)  # give the killer thread time to land the kill
        orig_checkpoint()

    cancellation.checkpoint = slow_checkpoint
    try:
        victim = threading.Thread(target=run_victim)
        victim.start()
        assert started.wait(5.0)
        # find the victim pid and kill it
        for entry in inst._process_list.snapshot():
            inst._process_list.kill(str(entry["id"]))
        victim.join(10.0)
    finally:
        cancellation.checkpoint = orig_checkpoint
    assert "was killed" in results.get("err", ""), results
