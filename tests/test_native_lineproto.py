"""Native line-protocol tokenizer parity (greptimedb_tpu/native).

The pure-Python parser is the behavioral spec; the C extension must
produce identical structures on every case, including the escape and
quoting corners.
"""

import time

import pytest

from greptimedb_tpu.servers import influx

native = pytest.importorskip("greptimedb_tpu.native._lineproto")


CASES = [
    "cpu,host=a,region=us usage=1.5 1000",
    "cpu usage=1.5",                                 # no tags, no ts
    'm,tag\\,x=va\\=l field=2i 5',                   # escaped , and =
    'm f1=1.5,f2=2i,f3=t,f4=F,f5="hi there" 7',      # all value types
    'weird\\ name,t=v f="a\\"b\\\\c" 9',             # escaped space+quote
    'm f="comma, inside" 1',
    "m value=-42i 2",
    "m value=1e-3 3",
    "  m spaced=1 4  ",                              # surrounding space
    "# comment line\nm a=1 5\n\nm b=2 6",            # comments + blanks
    'm,empty= f=1 8',                                # empty tag value
]

BAD = [
    "justonemeasurement",
    "m novalue 1",
    "m f=notanumber 1",
]


def _python_parse(payload):
    out = []
    for raw in payload.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        out.append(influx.parse_line(line))
    return out


@pytest.mark.parametrize("payload", CASES)
def test_native_matches_python(payload):
    assert native.parse_payload(payload) == _python_parse(payload)


@pytest.mark.parametrize("payload", BAD)
def test_native_rejects_like_python(payload):
    with pytest.raises(ValueError):
        native.parse_payload(payload)
    with pytest.raises(Exception):
        _python_parse(payload)


def test_value_types_exact():
    (m, tags, fields, ts), = native.parse_payload(
        'm f1=1.5,f2=2i,f3=t,f4="x"'
    )
    assert isinstance(fields["f1"], float)
    assert isinstance(fields["f2"], int) and not isinstance(
        fields["f2"], bool
    )
    assert fields["f3"] is True
    assert fields["f4"] == "x"
    assert ts is None


def test_native_is_faster():
    lines = "\n".join(
        f"cpu,host=h{i % 100},dc=dc{i % 5} "
        f"usage_user={i % 97}.5,usage_system={i % 13}i {i * 1000}"
        for i in range(20_000)
    )
    def best_of(fn, k=3):
        best = float("inf")
        out = None
        for _ in range(k):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return out, best

    a, t_native = best_of(lambda: native.parse_payload(lines))
    b, t_python = best_of(lambda: _python_parse(lines))
    assert a == b
    # the native tokenizer must actually pay for itself (min-of-3 to
    # tolerate scheduler noise on shared runners)
    assert t_native * 1.2 < t_python, (t_native, t_python)


def test_ingest_path_uses_native(tmp_path):
    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path / "d"), warm_start=False)
    try:
        n = influx.write_lines(
            inst, "lp,host=a v=1.5 1000000\nlp,host=b v=2.5 2000000",
            db="public", precision="us",
        )
        assert n == 2
        r = inst.sql("SELECT host, v FROM lp ORDER BY host")
        assert [list(x) for x in r.rows()] == [["a", 1.5], ["b", 2.5]]
    finally:
        inst.close()
