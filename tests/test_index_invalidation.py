"""Secondary tag index (index/): invalidation proof through the full
frontend -> datanode path.

The index answers matchers from per-region postings plus a
(matcher-set, registry-version) result cache. A stale posting set or
cached sid list after a data-mutating op — flush, compaction (incl.
the device merge), ALTER, truncate, DROP, region migration — would
ship wrong partials from the datanode. Every test runs the matcher
query with the index on, then clears every dist cache and re-runs it
with the index disabled (the registry's linear match is the oracle):
results must be bit-identical. Mirrors tests/test_dist_scan_cache.py.
"""

import contextlib

import pytest

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu import index as _index
from greptimedb_tpu.dist.client import MetaClient
from greptimedb_tpu.dist.frontend import DistInstance
from greptimedb_tpu.dist.region_server import RegionServer
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.flight import FlightFrontend
from greptimedb_tpu.servers.meta_http import MetasrvServer
from greptimedb_tpu.storage.compaction import CompactionOptions
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.telemetry.metrics import global_registry


class _Harness:
    def __init__(self, tmp_path, n_datanodes=2, *, store=None,
                 compaction=None):
        self.meta = MetasrvServer(
            addr="127.0.0.1", port=0, data_home=str(tmp_path / "meta")
        ).start()
        self.meta_addr = f"127.0.0.1:{self.meta.port}"
        self.datanodes = {}
        for i in range(n_datanodes):
            home = str(tmp_path / f"dn{i}")
            cfg = EngineConfig(data_root=home, enable_background=False)
            if compaction is not None:
                cfg.compaction = compaction
            inst = Standalone(
                engine_config=cfg, prefer_device=False,
                warm_start=False, store=store,
            )
            inst.region_server = RegionServer(inst.engine, home)
            fs = FlightFrontend(inst, port=0).start()
            MetaClient(self.meta_addr).register(
                i, f"127.0.0.1:{fs.server.port}"
            )
            self.datanodes[i] = (inst, fs)
        self.frontend = DistInstance(
            str(tmp_path / "fe"), self.meta_addr, prefer_device=False
        )

    def region_servers(self):
        return [inst.region_server for inst, _ in self.datanodes.values()]

    def clear_caches(self):
        """Drop every layer that could replay an index-era result to
        the oracle run: the frontend result cache and the datanode
        merged-scan caches."""
        self.frontend.result_cache.clear()
        for rs in self.region_servers():
            rs.scan_cache.clear()

    def close(self):
        self.frontend.close()
        for inst, fs in self.datanodes.values():
            fs.close()
            inst.close()
        self.meta.close()


@pytest.fixture()
def harness(tmp_path):
    h = _Harness(tmp_path)
    yield h
    h.close()


@contextlib.contextmanager
def index_disabled():
    _index.configure({"enable": False})
    try:
        yield
    finally:
        _index.configure({"enable": True})


# matcher-carrying queries: eq (a posting lookup), ne (dictionary-
# domain evaluation), and LIKE (a regex matcher)
QS = (
    "select host, sum(v), count(*) from t1 where host = 'h1' "
    "group by host order by host",
    "select host, sum(v), count(*) from t1 where host != 'h0' "
    "group by host order by host",
    "select host, count(*) from t1 where host like 'h%' "
    "group by host order by host",
)


def _assert_identical(h, queries=QS):
    fe = h.frontend
    got = [fe.sql(q).rows() for q in queries]
    h.clear_caches()
    with index_disabled():
        want = [fe.sql(q).rows() for q in queries]
    for g, w, q in zip(got, want, queries):
        assert g == w, f"index-on result diverged for: {q}"
    return got


def _seed(fe, rows=40):
    fe.execute_sql(
        "create table t1 (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 2)"
    )
    values = ", ".join(
        f"('h{i % 4}', {1_000_000 + i * 1000}, {float(i)})"
        for i in range(rows)
    )
    fe.execute_sql(f"insert into t1 (host, ts, v) values {values}")


def test_seeded_matcher_queries_identical(harness):
    _seed(harness.frontend)
    got = _assert_identical(harness)
    assert got[0]  # the eq query actually matched something


def test_flush_and_new_series_invalidate(harness):
    fe = harness.frontend
    _seed(fe)
    _assert_identical(harness)  # warm: result cache + postings built
    fe.catalog.table("public", "t1").flush()
    _assert_identical(harness)
    # a NEW series after the warm lookups: the registry version bump
    # must invalidate cached sid sets through the datanode path
    fe.execute_sql(
        "insert into t1 (host, ts, v) values ('h9', 99000000, 7.0)"
    )
    rows = fe.sql(
        "select host, sum(v) from t1 where host = 'h9' group by host"
    ).rows()
    assert rows == [["h9", 7.0]]
    _assert_identical(harness)


def test_compaction_device_merge_invalidates(tmp_path):
    """Compaction rewrites SSTs (fresh sid_min/sid_max footers) via the
    DEVICE merge path; matcher scans across the swap stay identical."""
    h = _Harness(
        tmp_path,
        compaction=CompactionOptions(device_merge_min_rows=1,
                                     verify_device_merge=True),
    )
    try:
        fe = h.frontend
        _seed(fe, rows=20)
        table = fe.catalog.table("public", "t1")
        table.flush()
        for round_ in range(4):  # enough L0 runs to trip the picker
            fe.execute_sql(
                "insert into t1 (host, ts, v) values "
                + ", ".join(
                    f"('h{i % 4}', "
                    f"{2_000_000 + round_ * 40_000 + i * 1000},"
                    f" {float(i)})"
                    for i in range(20)
                )
            )
            table.flush()
        _assert_identical(h)  # warm across both datanodes
        d0 = global_registry.get(
            "gtpu_compaction_merge_total"
        ).labels("device").value
        compacted = sum(1 for rp in table.regions if rp.compact())
        assert compacted > 0
        assert global_registry.get(
            "gtpu_compaction_merge_total"
        ).labels("device").value > d0
        _assert_identical(h)
    finally:
        h.close()


def test_alter_add_tag_invalidates(harness):
    """ALTER adding a tag widens the registry's tag set: the postings
    must rebuild (k changed) and matchers on the new tag must work."""
    fe = harness.frontend
    _seed(fe)
    _assert_identical(harness)  # warm with the old tag set
    fe.execute_sql("alter table t1 add column dc string primary key")
    fe.execute_sql(
        "insert into t1 (host, dc, ts, v) values "
        "('h0', 'east', 50000000, 1.0), ('h5', 'west', 50001000, 2.0)"
    )
    dc_qs = (
        "select host, sum(v) from t1 where dc = 'east' "
        "group by host order by host",
        "select host, sum(v) from t1 where dc != 'east' "
        "group by host order by host",
    )
    got = _assert_identical(harness, QS + dc_qs)
    assert got[3] == [["h0", 1.0]]


def test_truncate_then_refill_identical(harness):
    fe = harness.frontend
    _seed(fe)
    _assert_identical(harness)  # warm
    fe.catalog.table("public", "t1").truncate()
    assert fe.sql(
        "select count(*) from t1 where host = 'h1'"
    ).rows() == [[0]]
    _assert_identical(harness)
    fe.execute_sql(
        "insert into t1 (host, ts, v) values ('h1', 1000, 5.0)"
    )
    got = _assert_identical(harness)
    assert got[0] == [["h1", 5.0, 1]]


def test_drop_and_recreate_identical(harness):
    fe = harness.frontend
    _seed(fe)
    _assert_identical(harness)  # warm against the first incarnation
    fe.execute_sql("drop table t1")
    fe.execute_sql(
        "create table t1 (ts timestamp time index, host string "
        "primary key, v double) with (num_regions = 2)"
    )
    fe.execute_sql(
        "insert into t1 (host, ts, v) values ('h1', 1000, 42.0)"
    )
    got = _assert_identical(harness)
    assert got[0] == [["h1", 42.0, 1]]


def test_region_migration_identical(tmp_path):
    from greptimedb_tpu.storage.object_store import FsObjectStore

    shared = FsObjectStore(str(tmp_path / "shared_store"))
    h = _Harness(tmp_path, n_datanodes=2, store=shared)
    try:
        fe = h.frontend
        fe.execute_sql(
            "create table gm (ts timestamp time index, host string "
            "primary key, v double)"
        )
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 1000, 1.0), "
            "('b', 2000, 2.0)"
        )
        q = ("select host, sum(v) from gm where host = 'a' "
             "group by host order by host",)
        _assert_identical(h, q)  # warm on the source hosting
        ms = h.meta.metasrv
        rid = fe.catalog.table("public", "gm").info.region_ids()[0]
        src = ms.route_of(rid)
        ms.migrate_region(rid, 1 - src)
        fe.catalog.refresh()
        # the target hosting rebuilt its own registry + index
        got = _assert_identical(h, q)
        assert got[0] == [["a", 1.0]]
        fe.execute_sql(
            "insert into gm (host, ts, v) values ('a', 3000, 10.0)"
        )
        got = _assert_identical(h, q)
        assert got[0] == [["a", 11.0]]
    finally:
        h.close()
