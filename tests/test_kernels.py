"""Pallas kernel twins (parallel/kernels): interpret-mode parity against
the XLA collective paths on the 8-virtual-device CPU mesh, the plane
codec bit-exactness contract, and the fused compaction merge's
readback-is-output-only regression (ISSUE 17)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
import pytest

from greptimedb_tpu.parallel import dist, mesh as M
from greptimedb_tpu.parallel import kernels as K
from greptimedb_tpu.parallel.kernels import merge_gather as mg
from greptimedb_tpu.parallel.kernels import topk_merge as tm

NS = 8


@pytest.fixture(scope="module")
def mesh8():
    return M.make_mesh(jax.devices())  # shard=8, time=1


@pytest.fixture
def kernels_on():
    """Force the fused-merge planner gate open (and restore after):
    merge_rows reads mesh.global_mesh_opts(), not an engine."""
    with M._state_lock:
        old = M._global_opts
        M._global_opts = M.MeshOptions(
            enabled=False, pallas_kernels="on",
            pallas_min_rows=1, pallas_min_series=1,
        )
    yield
    with M._state_lock:
        M._global_opts = old


def _bits(a: np.ndarray) -> np.ndarray:
    """View through the unsigned twin so -0.0 vs +0.0 and NaN payloads
    compare by bit pattern."""
    a = np.asarray(a)
    if a.dtype.kind == "f":
        return a.view(np.dtype(f"u{a.dtype.itemsize}"))
    return a


def _smap(mesh, body, spec_in, *args):
    darg = [
        jax.device_put(jnp.asarray(a), NamedSharding(mesh, s))
        for a, s in zip(args, spec_in)
    ]
    return shard_map(
        body, mesh=mesh, in_specs=tuple(spec_in),
        out_specs=P(M.AXIS_SHARD), check_rep=False,
    )(*darg)


def test_ring_fold_bit_identical_to_gather_fold(mesh8, rng):
    fb, g, nb = 3, 5, 16
    x = rng.standard_normal((NS * fb, g, nb)).astype(np.float32)
    x[rng.random(x.shape) < 0.05] = -0.0
    x[rng.random(x.shape) < 0.05] = 0.0
    spec = [P(M.AXIS_SHARD, None, None)]

    def body_xla(parts):
        return dist.ShardFoldCtx(NS).fold_blocks(parts)[None]

    def body_ring(parts):
        return K.RingFoldCtx(NS, interpret=True).fold_blocks(parts)[None]

    a = np.asarray(_smap(mesh8, body_xla, spec, x))    # (NS, g, nb)
    b = np.asarray(_smap(mesh8, body_ring, spec, x))
    # identical on every shard, and bit-identical across paths
    for s in range(NS):
        assert np.array_equal(_bits(a[s]), _bits(a[0]))
        assert np.array_equal(_bits(b[s]), _bits(b[0]))
    assert np.array_equal(_bits(a), _bits(b))


@pytest.mark.parametrize("take_max", [False, True])
def test_ring_pext_matches_collective(mesh8, rng, take_max):
    g = 96
    # finite + ±inf payloads: the engine masks absent cells with ±inf
    # sentinels before pext, and NaN-vs-pmax semantics are backend
    # defined (the documented exception in README "Pallas kernels")
    x = rng.standard_normal((NS, g)).astype(np.float32)
    x[rng.random(x.shape) < 0.04] = np.inf
    x[rng.random(x.shape) < 0.04] = -np.inf
    x[rng.random(x.shape) < 0.04] = -0.0
    spec = [P(M.AXIS_SHARD, None)]

    def body_xla(xl):
        return dist.ShardFoldCtx(NS).pext(xl[0], take_max=take_max)[None]

    def body_ring(xl):
        ctx = K.RingFoldCtx(NS, interpret=True)
        return ctx.pext(xl[0], take_max=take_max)[None]

    a = np.asarray(_smap(mesh8, body_xla, spec, x))
    b = np.asarray(_smap(mesh8, body_ring, spec, x))
    assert np.array_equal(_bits(a), _bits(b))


def test_ring_psum_onehot_matches_psum(mesh8, rng):
    g = 128
    # masked one-nonzero payload: exactly one shard contributes per slot
    winner = rng.integers(0, NS, g)
    x = np.zeros((NS, g), np.float32)
    x[winner, np.arange(g)] = rng.standard_normal(g).astype(np.float32)
    spec = [P(M.AXIS_SHARD, None)]

    def body_xla(xl):
        return dist.ShardFoldCtx(NS).psum(xl[0])[None]

    def body_ring(xl):
        return K.RingFoldCtx(NS, interpret=True).psum(xl[0])[None]

    a = np.asarray(_smap(mesh8, body_xla, spec, x))
    b = np.asarray(_smap(mesh8, body_ring, spec, x))
    assert np.array_equal(_bits(a), _bits(b))


def test_ring_topk_merge_matches_all_gather_reselect(mesh8, rng):
    j, kl, k = 6, 5, 9
    key = rng.standard_normal((NS, j, kl)).astype(np.float32)
    # force cross-shard ties and absent (-inf) candidates
    key[rng.random(key.shape) < 0.2] = 0.5
    key[rng.random(key.shape) < 0.1] = -np.inf
    key = -np.sort(-key, axis=2)  # descending per shard, like top_k
    val = rng.standard_normal((NS, j, kl)).astype(np.float32)
    val[rng.random(val.shape) < 0.05] = -0.0
    idx = rng.integers(0, 10_000, (NS, j, kl)).astype(np.int32)
    pres = rng.random((NS, j, kl)) < 0.9
    spec = [P(M.AXIS_SHARD, None, None)] * 4

    def body_xla(ks, vs, is_, ps):
        cat = lambda x: jax.lax.all_gather(  # noqa: E731
            x[0], M.AXIS_SHARD, axis=1, tiled=True
        )
        c_key = cat(ks)
        f_key, f_pos = jax.lax.top_k(c_key, k)
        take = lambda p: jnp.take_along_axis(p, f_pos, axis=1)  # noqa: E731
        return jnp.stack([
            f_key, take(cat(vs)),
            take(cat(is_).astype(jnp.float32)),
            take(cat(ps)).astype(jnp.float32) * jnp.isfinite(f_key),
        ])[None]

    def body_ring(ks, vs, is_, ps):
        ok, ov, oi, op_ = tm.ring_topk_merge(
            ks[0], vs[0], is_[0], ps[0], k=k, ns=NS, interpret=True,
        )
        return jnp.stack([
            ok, ov, oi.astype(jnp.float32),
            (op_ & jnp.isfinite(ok)).astype(jnp.float32),
        ])[None]

    a = np.asarray(_smap(mesh8, body_xla, spec, key, val, idx, pres))
    b = np.asarray(_smap(mesh8, body_ring, spec, key, val, idx, pres))
    for s in range(NS):
        assert np.array_equal(_bits(b[s]), _bits(b[0]))
    # finite-key slots (real candidates) are bit-identical — values,
    # indices, tie-breaks; -inf fill slots are the documented exception
    fin = np.isfinite(a[0, 0])
    assert np.array_equal(fin, np.isfinite(b[0, 0]))
    for plane in range(4):
        pa, pb = a[0, plane][fin], b[0, plane][fin]
        assert np.array_equal(_bits(pa), _bits(pb)), plane


@pytest.mark.parametrize(
    "largest",
    [True, pytest.param(False, marks=pytest.mark.slow)],
)
def test_dist_topk_kernel_parity(mesh8, rng, largest):
    n, k = 256, 7
    vals = rng.standard_normal(n).astype(np.float32)  # continuous: no ties
    mask = rng.random(n) > 0.1
    sharding = dist.shard_rows_sharding(mesh8)
    dv = jax.device_put(jnp.array(vals), sharding)
    dm = jax.device_put(jnp.array(mask), sharding)
    v0, i0 = dist.dist_topk(mesh8, k, largest=largest)(dv, dm)
    v1, i1 = dist.dist_topk(mesh8, k, largest=largest,
                            kernel=True, interpret=True)(dv, dm)
    fin = np.isfinite(np.asarray(v0))
    assert np.array_equal(_bits(np.asarray(v0)[fin]),
                          _bits(np.asarray(v1)[fin]))
    assert np.array_equal(np.asarray(i0)[fin], np.asarray(i1)[fin])


def test_plane_codec_bit_exact_roundtrip():
    cases = [
        np.array([0, 1, -1, 2**62, -2**62, 2**63 - 1, -2**63],
                 np.int64),
        np.array([0.0, -0.0, np.nan, np.inf, -np.inf, 1.5e-310],
                 np.float64),
        np.array([0.0, -0.0, np.nan, np.inf, -np.inf, 1e-40],
                 np.float32),
        np.array([0, 1, 2**64 - 1, 2**32], np.uint64),
        np.array([-128, 0, 127], np.int8),
        np.array([True, False, True], np.bool_),
        np.array([0.5, -0.5, 65504.0], np.float16),
        np.array([0, 1, 2**40], "int64").view("datetime64[ms]"),
        np.array([3, 1, 4, 1, 5], np.uint16),
    ]
    for col in cases:
        assert mg.packable(col.dtype)
        planes = mg.pack_planes(col)
        assert planes.dtype == np.uint32
        assert planes.shape == (mg.plane_count(col.dtype), len(col))
        back = mg.unpack_planes(planes, col.dtype, len(col))
        assert back.dtype == col.dtype
        assert np.array_equal(col.view(np.uint8), back.view(np.uint8)), \
            col.dtype
    assert not mg.packable(np.dtype(object))
    assert not mg.packable(np.dtype("U4"))


def test_gather_planes_matches_host_take(rng):
    p, n, n_out = 5, 200, 64
    planes = rng.integers(0, 2**32, (p, n)).astype(np.uint32)
    src = rng.integers(0, n, n_out).astype(np.int32)
    run = mg.gather_program(p, n, n_out, True)
    got = np.asarray(run(jnp.asarray(planes), jnp.asarray(src)))
    assert np.array_equal(got, planes[:, src])


# ----------------------------------------------------------------------
# fused compaction merge: readback == output columns (satellite 2)
# ----------------------------------------------------------------------

def _merge_rows_input(n, seed=7):
    from greptimedb_tpu.storage.memtable import (
        OP_DELETE, OP_PUT, ColumnarRows,
    )

    rng = np.random.default_rng(seed)
    sid = rng.integers(0, 16, n).astype(np.int32)
    ts = rng.integers(0, 60, n).astype(np.int64) * 1000  # heavy dedup
    seq = np.arange(n, dtype=np.uint64)
    rng.shuffle(seq)
    op = np.where(rng.random(n) < 0.1, OP_DELETE, OP_PUT).astype(np.uint8)
    f = rng.standard_normal(n)
    f[rng.random(n) < 0.02] = np.nan
    return ColumnarRows(
        sid=sid, ts=ts, seq=seq, op=op,
        fields={"a": f, "b": rng.standard_normal(n).astype(np.float32)},
        field_valid={"a": rng.random(n) < 0.7, "b": rng.random(n) < 0.95},
    )


def test_fused_merge_readback_is_output_only(kernels_on):
    from greptimedb_tpu.query import readback
    from greptimedb_tpu.storage import device_merge as dm
    from greptimedb_tpu.storage.device_merge import host_merge, merge_rows

    n = 4000
    rows = _merge_rows_input(n)
    rb0 = readback.readback_bytes("full")
    out, path = merge_rows(rows, merge_mode="last_non_null",
                           drop_deletes=True, device_min_rows=1,
                           verify=True)
    fused_rb = readback.readback_bytes("full") - rb0
    assert path == "device"
    host = host_merge(_merge_rows_input(n), merge_mode="last_non_null",
                      drop_deletes=True)
    assert len(out) == len(host) < n // 2  # the dedup really happened
    # exact fused readback: the 4-byte kept-count plus the gathered
    # output planes — keep group (sid+ts+seq+op+valids) and one group
    # per backfilled field (value+valid). NOTHING proportional to the
    # input row count (the classic path reads order/keep/fills back at
    # O(input pad)).
    n_out = dm._pad_to_bucket(len(out))
    keep_planes = 1 + 2 + 2 + 1                # sid ts seq op
    grp_a = 2 + 1     # backfilled f64 field + its valid (own src group)
    grp_b = 1 + 1     # backfilled f32 field + its valid
    expected = 4 + 4 * n_out * (keep_planes + grp_a + grp_b)
    assert fused_rb == expected, (fused_rb, expected)
    # regression pin: the classic per-input-run index readback
    # (order int64 + keep bool + two int64 fill maps over the input
    # bucket) does not come back on the fused path
    pad = dm._pad_to_bucket(n)
    classic_rb = pad * (8 + 1 + 8 + 8)
    assert fused_rb < classic_rb


def test_fused_merge_records_kernel_decision(kernels_on):
    from greptimedb_tpu.storage.device_merge import merge_rows
    from greptimedb_tpu.telemetry.metrics import global_registry

    ctr = global_registry.counter(
        "gtpu_mesh_queries_total",
        "Mesh execution decisions by mode/reason/site",
        labels=("kind", "mode", "reason"),
    ).labels("merge_kernel", "pallas", "fused_gather")
    before = ctr.value
    _out, path = merge_rows(_merge_rows_input(2048),
                            merge_mode="last_row", drop_deletes=False,
                            device_min_rows=1, verify=True)
    assert path == "device"
    assert ctr.value == before + 1


def test_collective_attribution_on_program_registry():
    from greptimedb_tpu.telemetry import device_programs, device_trace
    from greptimedb_tpu.telemetry.metrics import global_registry

    fn = jax.jit(lambda x: x * 2)
    with device_trace.device_call(
            "kernel_attr_test", key=("k", 1),
            collective=True, comm_bytes=12345) as d:
        out = d.run(fn, jnp.arange(8.0))
        out.block_until_ready()
        d.executed()
    rows = [r for r in device_programs.global_programs.snapshot(
        analyze=False) if r["site"] == "kernel_attr_test"]
    assert rows and rows[0]["collective"] is True
    assert rows[0]["comm_bytes"] == 12345
    text = global_registry.render()
    assert "gtpu_device_program_comm_bytes_total" in text
    assert 'site="kernel_attr_test"' in text
