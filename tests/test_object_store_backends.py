"""S3-compatible object store + cache layers + pluggable remote WAL
(VERDICT missing #6).

The mini-S3 server below speaks the real REST surface the store uses
(GET/PUT/DELETE/HEAD, ListObjectsV2 XML, Range) and asserts every
request carries a SigV4 authorization header — the same wire shape a
MinIO/AWS endpoint expects.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.storage.engine import EngineConfig
from greptimedb_tpu.storage.object_store import (
    CachedObjectStore,
    MemoryObjectStore,
    S3ObjectStore,
)
from greptimedb_tpu.storage.wal import ObjectStoreLogStore


class _MiniS3(BaseHTTPRequestHandler):
    store: dict
    requests_seen: list

    def log_message(self, *a):
        pass

    def _key(self):
        # /bucket/key...
        path = self.path.split("?")[0]
        parts = path.lstrip("/").split("/", 1)
        return parts[1] if len(parts) > 1 else ""

    def _check_auth(self):
        auth = self.headers.get("Authorization", "")
        assert auth.startswith("AWS4-HMAC-SHA256"), "missing sigv4"
        type(self).requests_seen.append(self.command)

    def do_PUT(self):
        self._check_auth()
        n = int(self.headers.get("Content-Length", 0) or 0)
        type(self).store[self._key()] = self.rfile.read(n)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        self._check_auth()
        if "list-type=2" in self.path:
            import urllib.parse as up

            q = up.parse_qs(up.urlparse(self.path).query)
            prefix = q.get("prefix", [""])[0]
            keys = sorted(k for k in type(self).store if
                          k.startswith(prefix))
            body = (
                "<?xml version=\"1.0\"?><ListBucketResult>"
                + "".join(
                    f"<Contents><Key>{k}</Key>"
                    f"<Size>{len(type(self).store[k])}</Size></Contents>"
                    for k in keys
                )
                + "</ListBucketResult>"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = type(self).store.get(self._key())
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self.headers.get("Range")
        if rng:
            lo, hi = rng.removeprefix("bytes=").split("-")
            data = data[int(lo):int(hi) + 1]
        self.send_response(206 if rng else 200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_HEAD(self):
        self._check_auth()
        ok = self._key() in type(self).store
        self.send_response(200 if ok else 404)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        self._check_auth()
        type(self).store.pop(self._key(), None)
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()


@pytest.fixture()
def mini_s3():
    handler = type("H", (_MiniS3,), {"store": {}, "requests_seen": []})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1], handler
    srv.shutdown()
    srv.server_close()


def _s3(port):
    return S3ObjectStore(
        bucket="test", endpoint=f"127.0.0.1:{port}",
        access_key_id="ak", secret_access_key="sk",
    )


def test_s3_store_roundtrip(mini_s3):
    port, handler = mini_s3
    s3 = _s3(port)
    s3.write("a/b.txt", b"hello world")
    assert s3.read("a/b.txt") == b"hello world"
    assert s3.read_range("a/b.txt", 6, 5) == b"world"
    assert s3.exists("a/b.txt") and not s3.exists("a/nope")
    s3.write("a/c.txt", b"x")
    assert [m.path for m in s3.list("a/")] == ["a/b.txt", "a/c.txt"]
    assert s3.list("a/")[0].size == 11
    s3.delete("a/b.txt")
    with pytest.raises(FileNotFoundError):
        s3.read("a/b.txt")
    assert "PUT" in handler.requests_seen   # sigv4 asserted per request


def test_cached_store_hits_and_evicts(tmp_path, mini_s3):
    port, handler = mini_s3
    cached = CachedObjectStore(_s3(port), str(tmp_path / "cache"),
                               max_bytes=100)
    cached.write("k1", b"a" * 60)
    handler.requests_seen.clear()
    assert cached.read("k1") == b"a" * 60
    assert handler.requests_seen == []       # served from cache
    cached.write("k2", b"b" * 60)            # evicts k1 (100-byte cap)
    handler.requests_seen.clear()
    assert cached.read("k1") == b"a" * 60    # refetched from s3
    assert "GET" in handler.requests_seen
    # read_range served from cached copy
    handler.requests_seen.clear()
    assert cached.read_range("k1", 0, 5) == b"aaaaa"
    assert handler.requests_seen == []
    # delete drops both layers
    cached.delete("k1")
    assert not cached.exists("k1")


def test_object_store_log_store(tmp_path):
    store = MemoryObjectStore()
    ls = ObjectStoreLogStore(store, "wal/region_1")
    assert ls.append(b"one") == 0
    assert ls.append_batch([b"two", b"three"]) == 2
    got = [e.payload for e in ls.replay(0)]
    assert got == [b"one", b"two", b"three"]
    assert [e.entry_id for e in ls.replay(1)] == [1, 2]
    # a second instance over the same store resumes ids (failover shape)
    ls2 = ObjectStoreLogStore(store, "wal/region_1")
    assert ls2.next_entry_id == 3
    ls2.obsolete(0)
    assert [e.payload for e in ls2.replay(0)] == [b"two", b"three"]
    # obsoleting EVERYTHING keeps the tail segment so a restart still
    # recovers the id sequence (ids below the flushed mark would
    # otherwise make post-restart appends unreplayable)
    ls2.obsolete(2)
    ls3 = ObjectStoreLogStore(store, "wal/region_1")
    assert ls3.next_entry_id == 3
    assert ls3.append(b"four") == 3
    assert [e.entry_id for e in ls3.replay(3)] == [3]


def test_cached_store_no_stale_file_after_uncacheable_update(tmp_path,
                                                             mini_s3):
    port, _ = mini_s3
    cdir = str(tmp_path / "cache")
    cached = CachedObjectStore(_s3(port), cdir, max_bytes=100)
    cached.write("k", b"old")
    cached.write("k", b"x" * 200)     # exceeds cache cap: uncacheable
    # a NEW cache instance over the same dir must not resurrect "old"
    cached2 = CachedObjectStore(_s3(port), cdir, max_bytes=100)
    assert cached2.read("k") == b"x" * 200


def test_engine_on_s3_with_remote_wal(tmp_path, mini_s3):
    """Full engine over the S3 store with the object-store WAL: ingest
    with durability, reopen from the same bucket, data survives."""
    port, _ = mini_s3
    cfg = EngineConfig(data_root=str(tmp_path / "d1"),
                       enable_background=False, wal_backend="object")
    inst = Standalone(engine_config=cfg, store=_s3(port),
                      warm_start=False)
    inst.sql("CREATE TABLE s3t (host STRING, v DOUBLE, ts TIMESTAMP "
             "TIME INDEX, PRIMARY KEY (host))")
    inst.sql("INSERT INTO s3t (host, v, ts) VALUES ('a', 1.5, 1000), "
             "('b', 2.5, 2000)")
    inst.close()

    # a DIFFERENT node (fresh data_root) opens the same bucket: catalog,
    # WAL and data all come from shared storage
    cfg2 = EngineConfig(data_root=str(tmp_path / "d2"),
                        enable_background=False, wal_backend="object")
    inst2 = Standalone(engine_config=cfg2, store=_s3(port),
                       warm_start=False)
    try:
        r = inst2.sql("SELECT host, v FROM s3t ORDER BY host")
        assert [list(x) for x in r.rows()] == [["a", 1.5], ["b", 2.5]]
    finally:
        inst2.close()
