"""Device-resident flow state (VERDICT r2 task #6): equivalence with the
host accumulator path, and a >=100k-group tick through one device
program."""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone

T0 = 1_700_000_000_000


@pytest.fixture()
def inst(tmp_path):
    s = Standalone(str(tmp_path / "data"))
    s.enable_flows(tick_interval_s=3600)  # manual ticks only
    yield s
    s.close()


def _setup(inst, flow_sql):
    inst.sql(
        "CREATE TABLE src (host STRING, v DOUBLE, ts TIMESTAMP TIME INDEX, "
        "PRIMARY KEY (host))"
    )
    inst.sql(flow_sql)


def _ingest(inst, hosts, vals, ts):
    inst.sql(
        "INSERT INTO src (host, v, ts) VALUES "
        + ", ".join(
            f"('{h}', {v}, {t})" for h, v, t in zip(hosts, vals, ts)
        )
    )


FLOW_SQL = (
    "CREATE FLOW f1 SINK TO out1 AS "
    "SELECT host, count(v) AS c, sum(v) AS s, avg(v) AS a, min(v) AS lo, "
    "max(v) AS hi, last_value(v ORDER BY ts) AS lv, stddev_pop(v) AS sd "
    "FROM src GROUP BY host"
)


def _sink_rows(inst, table="out1", order="host"):
    r = inst.sql(f"SELECT * FROM {table} ORDER BY {order}")
    return {tuple(row[:1]): row[1:] for row in
            ([list(x) for x in r.rows()])}


def test_device_state_used_and_matches_host(inst, monkeypatch):
    _setup(inst, FLOW_SQL)
    flow = inst.flows._flows["f1"]
    assert flow.device_state is not None, "expected the device state path"

    _ingest(inst, ["a", "b", "a"], [1.0, 5.0, 3.0], [T0, T0, T0 + 1000])
    _ingest(inst, ["a", "b", "c"], [7.0, 2.0, 9.0],
            [T0 + 2000, T0 + 3000, T0])
    inst.flows.flush_all()
    got = {k[0]: v for k, v in _sink_rows(inst).items()}

    # independent host-path run: same flow logic with device state off
    inst.sql("DROP FLOW f1")
    inst.sql("DROP TABLE out1")
    inst.sql(FLOW_SQL.replace("f1", "f2").replace("out1", "out2"))
    flow2 = inst.flows._flows["f2"]
    flow2.device_state = None  # force host accumulators
    _ingest(inst, ["a", "b", "a"], [1.0, 5.0, 3.0], [T0, T0, T0 + 1000])
    _ingest(inst, ["a", "b", "c"], [7.0, 2.0, 9.0],
            [T0 + 2000, T0 + 3000, T0])
    inst.flows.flush_all()
    want = {k[0]: v for k, v in _sink_rows(inst, "out2").items()}

    assert set(got) == set(want) == {"a", "b", "c"}
    for h in got:
        # [count, sum, avg, min, max, last, stddev] (+update_at ignored)
        np.testing.assert_allclose(
            [float(x) for x in got[h][:7]],
            [float(x) for x in want[h][:7]],
            rtol=1e-6, err_msg=h,
        )


def test_incremental_updates_accumulate(inst):
    _setup(inst, FLOW_SQL)
    _ingest(inst, ["a"], [2.0], [T0])
    inst.flows.flush_all()
    _ingest(inst, ["a"], [4.0], [T0 + 1000])
    inst.flows.flush_all()
    got = {k[0]: v for k, v in _sink_rows(inst).items()}
    c, s, a, lo, hi, lv = [float(x) for x in got["a"][:6]]
    assert (c, s, a, lo, hi, lv) == (2.0, 6.0, 3.0, 2.0, 4.0, 4.0)


def test_100k_groups_one_program(inst):
    """A tick over >=100k groups runs the ONE finalize program and writes
    every group back correctly."""
    inst.sql(
        "CREATE TABLE big (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW bigf SINK TO bigout AS "
        "SELECT host, sum(v) AS s, count(v) AS c FROM big GROUP BY host"
    )
    flow = inst.flows._flows["bigf"]
    assert flow.device_state is not None
    n = 120_000
    table = inst.catalog.table("public", "big")
    hosts = np.asarray([f"h{i:06d}" for i in range(n)], object)
    data = {
        "host": hosts,
        "ts": np.full(n, T0, np.int64),
        "v": np.arange(n, dtype=np.float64),
    }
    valid = {k: np.ones(n, bool) for k in data}
    inst._write_columns(table, data, valid)
    inst._notify_flows("public", "big", table, data, valid)
    assert flow.device_state.num_groups == n
    inst.flows.flush_all()
    r = inst.sql("SELECT count(*), sum(s), sum(c) FROM bigout")
    row = r.rows()[0]
    assert row[0] == n
    assert float(row[1]) == float(np.arange(n).sum())
    assert float(row[2]) == float(n)
    # second delta touches two groups only: dirty slice stays small
    data2 = {
        "host": np.asarray(["h000000", "h000001"], object),
        "ts": np.full(2, T0 + 1000, np.int64),
        "v": np.asarray([100.0, 200.0]),
    }
    valid2 = {k: np.ones(2, bool) for k in data2}
    inst._write_columns(table, data2, valid2)
    inst._notify_flows("public", "big", table, data2, valid2)
    assert int(flow.device_state.dirty.sum()) == 2
    inst.flows.flush_all()
    r = inst.sql("SELECT s FROM bigout WHERE host = 'h000000'")
    assert float(r.cols[0].values[0]) == 100.0


def test_expiry_compacts_device_state(inst):
    inst.sql(
        "CREATE TABLE esrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW ef SINK TO eout EXPIRE AFTER '1h' AS "
        "SELECT date_bin('1 minute', ts) AS w, sum(v) AS s "
        "FROM esrc GROUP BY date_bin('1 minute', ts)"
    )
    flow = inst.flows._flows["ef"]
    assert flow.device_state is not None
    import time as _t

    now = int(_t.time() * 1000)
    old = now - 7_200_000   # 2h ago: beyond EXPIRE AFTER '1h'
    _ingest_table(inst, "esrc", ["x", "y"], [1.0, 2.0], [old, now])
    inst.flows.flush_all()
    assert flow.device_state.num_groups == 1  # expired window dropped


def _ingest_table(inst, table, hosts, vals, ts):
    inst.sql(
        f"INSERT INTO {table} (host, v, ts) VALUES "
        + ", ".join(
            f"('{h}', {v}, {t})" for h, v, t in zip(hosts, vals, ts)
        )
    )


def test_expiry_shrinks_large_state(inst):
    """Compacting from >1024 groups down to a handful must not corrupt
    the device arrays (regression: expire() used to crash resizing the
    dirty mask and left the state unusable)."""
    inst.sql(
        "CREATE TABLE esrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW ef SINK TO eout EXPIRE AFTER '1h' AS "
        "SELECT date_bin('1 minute', ts) AS w, host, sum(v) AS s "
        "FROM esrc GROUP BY date_bin('1 minute', ts), host"
    )
    flow = inst.flows._flows["ef"]
    assert flow.device_state is not None
    import time as _t

    now = int(_t.time() * 1000)
    n = 3000
    table = inst.catalog.table("public", "esrc")
    data = {
        "host": np.asarray([f"h{i}" for i in range(n)], object),
        "ts": np.full(n, now, np.int64),
        "v": np.ones(n),
    }
    valid = {k: np.ones(n, bool) for k in data}
    inst._write_columns(table, data, valid)
    inst._notify_flows("public", "esrc", table, data, valid)
    assert flow.device_state.num_groups == n
    inst.flows.flush_all()
    # shrink the window so every ingested group is now expired
    flow.expire_after_s = -60
    inst.flows.flush_all()          # everything expires
    assert flow.device_state.num_groups == 0
    flow.expire_after_s = 3600
    # state stays usable after the compaction
    _ingest_table(inst, "esrc", ["a"], [5.0], [now])
    inst.flows.flush_all()
    r = inst.sql("SELECT s FROM eout WHERE host = 'a'")
    assert float(r.cols[0].values[-1]) == 5.0


def test_keyless_flow_uses_device_and_matches(inst):
    inst.sql(
        "CREATE TABLE ksrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW kf SINK TO kout AS "
        "SELECT count(v) AS c, sum(v) AS s FROM ksrc"
    )
    flow = inst.flows._flows["kf"]
    assert flow.device_state is not None
    _ingest_table(inst, "ksrc", ["a", "b"], [2.0, 3.0], [T0, T0 + 1])
    inst.flows.flush_all()
    r = inst.sql("SELECT c, s FROM kout")
    assert int(r.cols[0].values[-1]) == 2
    assert float(r.cols[1].values[-1]) == 5.0


def test_first_value_tie_prefers_first_arrival(inst):
    inst.sql(
        "CREATE TABLE fsrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW ff SINK TO fout AS "
        "SELECT host, first_value(v ORDER BY ts) AS fv, "
        "last_value(v ORDER BY ts) AS lv FROM fsrc GROUP BY host"
    )
    flow = inst.flows._flows["ff"]
    assert flow.device_state is not None
    # same host, same timestamp: host semantics keep the first arrival
    # for first_value and the last arrival for last_value
    _ingest_table(inst, "fsrc", ["a", "a", "a"], [1.0, 2.0, 3.0],
                  [T0, T0, T0])
    inst.flows.flush_all()
    r = inst.sql("SELECT fv, lv FROM fout WHERE host = 'a'")
    assert float(r.cols[0].values[-1]) == 1.0
    assert float(r.cols[1].values[-1]) == 3.0
    # a later batch at the SAME ts: first keeps, last replaces
    _ingest_table(inst, "fsrc", ["a"], [9.0], [T0])
    inst.flows.flush_all()
    r = inst.sql("SELECT fv, lv FROM fout WHERE host = 'a'")
    assert float(r.cols[0].values[-1]) == 1.0
    assert float(r.cols[1].values[-1]) == 9.0


def test_all_null_sum_is_null(inst):
    inst.sql(
        "CREATE TABLE nsrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW nf SINK TO nout AS "
        "SELECT host, sum(v) AS s, count(v) AS c FROM nsrc GROUP BY host"
    )
    flow = inst.flows._flows["nf"]
    assert flow.device_state is not None
    inst.sql(f"INSERT INTO nsrc (host, v, ts) VALUES ('a', NULL, {T0})")
    inst.flows.flush_all()
    r = inst.sql("SELECT s, c FROM nout WHERE host = 'a'")
    col = r.cols[0]
    assert col.validity is not None and not bool(col.validity[-1])
    assert int(r.cols[1].values[-1]) == 0


def test_null_key_distinct_from_none_string(inst):
    """NULL and the literal string 'None' in a key column are distinct
    groups on the device path, matching the host path."""
    inst.sql(
        "CREATE TABLE msrc (host STRING, v DOUBLE, ts TIMESTAMP TIME "
        "INDEX, PRIMARY KEY (host))"
    )
    inst.sql(
        "CREATE FLOW mf SINK TO mout AS "
        "SELECT host, sum(v) AS s FROM msrc GROUP BY host"
    )
    flow = inst.flows._flows["mf"]
    assert flow.device_state is not None
    inst.sql(
        f"INSERT INTO msrc (host, v, ts) VALUES "
        f"('None', 1.0, {T0}), (NULL, 10.0, {T0})"
    )
    assert flow.device_state.num_groups == 2


def test_demotion_preserves_state(inst):
    """A batch the device encoding can't take (negative ts) demotes the
    flow to the host path without losing accumulated state."""
    _setup(inst, FLOW_SQL)
    flow = inst.flows._flows["f1"]
    assert flow.device_state is not None
    _ingest(inst, ["a"], [2.0], [T0])
    _ingest(inst, ["a"], [4.0], [-5])   # pre-epoch ts: demote
    assert flow.device_state is None
    inst.flows.flush_all()
    got = {k[0]: v for k, v in _sink_rows(inst).items()}
    c, s = [float(x) for x in got["a"][:2]]
    assert (c, s) == (2.0, 6.0)
