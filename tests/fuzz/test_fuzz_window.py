"""Property fuzz: window functions vs a brute-force oracle.

Random frames/specs/data checked against a per-row O(n^2) reference
implementation of the SQL default-frame semantics (the engine's
vectorized path lives in query/window_fns.py).
"""

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone

N_CASES = 12


def _oracle(rows, func, part_key, order_keys, mode):
    """rows: list of dicts with k/host/ts/v. Returns {k: value|None}.
    mode: 'running' (RANGE peers) | 'rows' | 'whole'."""
    out = {}
    for i, r in enumerate(rows):
        part = [
            (j, s) for j, s in enumerate(rows)
            if part_key is None or s[part_key] == r[part_key]
        ]
        part.sort(key=lambda js: tuple(js[1][k] for k in order_keys)
                  + (js[0],))
        pos = next(p for p, (j, _) in enumerate(part) if j == i)
        if order_keys and mode == "running":
            me = tuple(r[k] for k in order_keys)
            frame = [s for _, s in part
                     if tuple(s[k] for k in order_keys) <= me]
        elif order_keys and mode == "rows":
            frame = [s for _, s in part[:pos + 1]]
        else:
            frame = [s for _, s in part]
        vals = [s["v"] for s in frame if s["v"] is not None]
        if func == "row_number":
            out[r["k"]] = pos + 1
        elif func == "rank":
            me = tuple(r[k] for k in order_keys)
            out[r["k"]] = 1 + sum(
                1 for _, s in part
                if tuple(s[k] for k in order_keys) < me
            )
        elif func == "dense_rank":
            me = tuple(r[k] for k in order_keys)
            distinct_before = {
                tuple(s[k] for k in order_keys) for _, s in part
                if tuple(s[k] for k in order_keys) < me
            }
            out[r["k"]] = len(distinct_before) + 1
        elif func == "count":
            out[r["k"]] = len(vals)
        elif func == "sum":
            out[r["k"]] = sum(vals) if vals else None
        elif func == "avg":
            out[r["k"]] = sum(vals) / len(vals) if vals else None
        elif func == "min":
            out[r["k"]] = min(vals) if vals else None
        elif func == "max":
            out[r["k"]] = max(vals) if vals else None
        elif func == "first_value":
            out[r["k"]] = frame[0]["v"]
        elif func == "last_value":
            out[r["k"]] = frame[-1]["v"]
        elif func == "lag":
            out[r["k"]] = part[pos - 1][1]["v"] if pos >= 1 else None
        elif func == "lead":
            out[r["k"]] = (part[pos + 1][1]["v"]
                           if pos + 1 < len(part) else None)
        else:
            raise AssertionError(func)
    return out


@pytest.mark.parametrize("seed", range(N_CASES))
def test_window_vs_oracle(tmp_path, seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(20, 120))
    hosts = [f"h{int(x)}" for x in rng.integers(0, 4, n)]
    # unique (ts, k) ordering removes intra-peer ambiguity for the
    # order-sensitive functions; ts alone has ties on purpose
    ts = [int(x) * 1000 for x in rng.integers(0, n // 3 + 2, n)]
    v = [None if rng.random() < 0.15 else round(float(x), 3)
         for x in rng.normal(50, 20, n)]
    rows = [{"k": i, "host": hosts[i], "ts": ts[i], "v": v[i]}
            for i in range(n)]

    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table t (rts timestamp time index, k bigint, "
            "host string primary key, ts bigint, v double)"
        )
        vals = ", ".join(
            f"({i}, {r['k']}, '{r['host']}', {r['ts']}, "
            f"{'NULL' if r['v'] is None else r['v']})"
            for i, r in enumerate(rows)
        )
        inst.execute_sql(
            f"insert into t (rts, k, host, ts, v) values {vals}"
        )

        func = str(rng.choice([
            "row_number", "rank", "dense_rank", "count", "sum", "avg",
            "min", "max", "first_value", "last_value", "lag", "lead",
        ]))
        partition = bool(rng.random() < 0.6)
        part_sql = "PARTITION BY host " if partition else ""
        part_key = "host" if partition else None
        order_sensitive = func in (
            "row_number", "first_value", "last_value", "lag", "lead",
        )
        # order-sensitive funcs get a unique composite key (ts, k)
        order_keys = ["ts", "k"] if order_sensitive else ["ts"]
        order_sql = "ORDER BY " + ", ".join(order_keys)
        frame_mode = "running"
        frame_sql = ""
        if func in ("count", "sum", "avg", "min", "max"):
            pick = rng.random()
            if pick < 0.33:
                frame_mode = "rows"
                frame_sql = (" ROWS BETWEEN UNBOUNDED PRECEDING "
                             "AND CURRENT ROW")
            elif pick < 0.55:
                frame_mode = "whole"
                order_sql = ""
        args = "v" if func not in (
            "row_number", "rank", "dense_rank",
        ) else ""
        if func == "count" and rng.random() < 0.5:
            args = "*"
        q = (f"SELECT k, {func}({args}) OVER ({part_sql}{order_sql}"
             f"{frame_sql}) AS w FROM t")
        res = inst.sql(q)
        got = {int(k): w for k, w in zip(res.cols[0].values,
                                         [None if not val else x
                                          for x, val in zip(
                                              res.cols[1].values,
                                              res.cols[1].valid_mask)])}
        want = _oracle(
            rows, func, part_key,
            order_keys if order_sql else [], frame_mode,
        )
        if func == "count" and args == "*":
            want = _oracle(
                [dict(r, v=0.0) for r in rows], "count", part_key,
                order_keys if order_sql else [], frame_mode,
            )
        for k in want:
            g, w = got[k], want[k]
            if w is None or g is None:
                assert g == w, (q, k, g, w)
            else:
                assert float(g) == pytest.approx(float(w), rel=1e-9), \
                    (q, k, g, w)
    finally:
        inst.close()
