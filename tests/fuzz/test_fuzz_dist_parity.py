"""Dist/standalone parity fuzz (ISSUE 2 satellite): random decomposable
aggregate / range / plain queries run against the SAME data both
standalone and through the distributed partial-plan pushdown
(frontend -> 3 datanodes over real sockets), asserting identical
results — the merge bugs the golden suite's fixed shapes miss.

Deterministic by default (seeded); set GREPTIMEDB_TPU_FUZZ_SEED to
explore, GREPTIMEDB_TPU_FUZZ_ITERS to lengthen. Defaults generate
7 batches x 30 = 210 compared queries.
"""

import os

import numpy as np
import pytest

pytest.importorskip("pyarrow.flight")

from greptimedb_tpu.dist.client import MetaClient
from greptimedb_tpu.dist.frontend import DistInstance
from greptimedb_tpu.dist.region_server import RegionServer
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.flight import FlightFrontend
from greptimedb_tpu.servers.meta_http import MetasrvServer
from greptimedb_tpu.storage.engine import EngineConfig

SEED = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_SEED", "20260803"))
BATCHES = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_ITERS", "7"))
PER_BATCH = 30

TAGS = ["t0", "t1"]
FIELDS = ["f0", "f1"]
PLAIN_AGGS = ["count", "sum", "min", "max", "avg", "stddev", "var"]
RANGE_AGGS = ["count", "sum", "min", "max", "avg",
              "first_value", "last_value"]
FILLS = ["", " FILL NULL", " FILL PREV", " FILL 0"]


@pytest.fixture(scope="module")
def topo(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dist_parity")
    meta = MetasrvServer(addr="127.0.0.1", port=0,
                         data_home=str(tmp / "meta")).start()
    meta_addr = f"127.0.0.1:{meta.port}"
    dns = []
    for i in range(3):
        home = str(tmp / f"dn{i}")
        inst = Standalone(
            engine_config=EngineConfig(data_root=home,
                                       enable_background=False),
            prefer_device=False, warm_start=False,
        )
        inst.region_server = RegionServer(inst.engine, home)
        fs = FlightFrontend(inst, port=0).start()
        MetaClient(meta_addr).register(i, f"127.0.0.1:{fs.server.port}")
        dns.append((inst, fs))
    fe = DistInstance(str(tmp / "fe"), meta_addr, prefer_device=False)
    ref = Standalone(str(tmp / "ref"), prefer_device=False,
                     warm_start=False)
    _seed_both(fe, ref)
    yield fe, ref
    fe.close()
    ref.close()
    for inst, fs in dns:
        fs.close()
        inst.close()
    meta.close()


def _seed_both(fe, ref, n_rows=160):
    ddl = (
        "create table fz (ts timestamp time index, t0 string, t1 string, "
        "f0 double, f1 double, primary key (t0, t1))"
    )
    fe.execute_sql(ddl + " with (num_regions = 3)")
    ref.execute_sql(ddl)
    rng = np.random.default_rng(SEED)
    parts = []
    for i in range(n_rows):
        t0 = f"a{int(rng.integers(0, 5))}"
        t1 = f"b{int(rng.integers(0, 3))}"
        ts = int(rng.integers(0, 60)) * 1000
        f0 = "NULL" if rng.random() < 0.08 else \
            f"{rng.random() * 200 - 100:.4f}"
        f1 = "NULL" if rng.random() < 0.08 else \
            f"{rng.random() * 50:.4f}"
        parts.append(f"('{t0}', '{t1}', {ts}, {f0}, {f1})")
    sql = ("insert into fz (t0, t1, ts, f0, f1) values "
           + ", ".join(parts))
    fe.execute_sql(sql)
    ref.execute_sql(sql)


def _random_query(rng) -> tuple[str, bool]:
    """(sql, expect_pushdown): deterministic-order decomposable shapes."""
    kind = rng.choice(["agg", "agg", "range", "range", "plain",
                       "count_distinct"])
    f = rng.choice(FIELDS)
    if kind == "agg":
        agg = rng.choice(PLAIN_AGGS)
        nkeys = int(rng.integers(0, 3))
        keys = list(rng.choice(TAGS, size=nkeys, replace=False))
        where = ""
        if rng.random() < 0.3:
            where = f" WHERE {rng.choice(TAGS)} != 'a0'"
        having = ""
        if keys and rng.random() < 0.25:
            having = " HAVING c > 0"
        sel = ", ".join(keys + [f"{agg}({f}) AS a", "count(*) AS c"])
        group = f" GROUP BY {', '.join(keys)}" if keys else ""
        order = f" ORDER BY {', '.join(keys)}" if keys else ""
        return (f"SELECT {sel} FROM fz{where}{group}{having}{order}",
                True)
    if kind == "count_distinct":
        k = rng.choice(TAGS)
        other = TAGS[1 - TAGS.index(k)]
        return (
            f"SELECT {k}, count(distinct {other}) FROM fz "
            f"GROUP BY {k} ORDER BY {k}",
            True,
        )
    if kind == "range":
        agg = rng.choice(RANGE_AGGS)
        rng_s = int(rng.integers(1, 4)) * 5
        align = int(rng.integers(1, 3)) * 5
        fill = rng.choice(FILLS)
        where = ""
        if rng.random() < 0.3:
            where = f" WHERE t0 != 'a1'"
        limit = ""
        if rng.random() < 0.25:
            limit = f" LIMIT {int(rng.integers(5, 40))}"
        # BY must cover the FULL tag set for the pushdown (series are
        # hash-routed by the full tuple, so groups stay disjoint)
        return (
            f"SELECT ts, t0, t1, {agg}({f}) RANGE '{rng_s}s'{fill} "
            f"FROM fz{where} ALIGN '{align}s' BY (t0, t1) "
            f"ORDER BY ts, t0, t1{limit}",
            True,
        )
    # plain: unique total order (t0, t1, ts) after last-write-wins dedup
    cmp = f"{rng.random() * 100 - 50:.2f}"
    limit = ""
    if rng.random() < 0.5:
        limit = f" LIMIT {int(rng.integers(3, 30))}"
    distinct = "DISTINCT " if rng.random() < 0.2 else ""
    return (
        f"SELECT {distinct}t0, t1, ts, {f} FROM fz WHERE {f} > {cmp} "
        f"ORDER BY t0, t1, ts{limit}",
        True,
    )


def _match(a, b) -> bool:
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if va is None or vb is None:
                if (va is None) != (vb is None):
                    return False
            elif isinstance(va, float) or isinstance(vb, float):
                if not np.isclose(float(va), float(vb),
                                  rtol=2e-4, atol=1e-3, equal_nan=True):
                    return False
            elif va != vb:
                return False
    return True


@pytest.mark.parametrize("batch", range(BATCHES))
def test_dist_parity_fuzz(topo, batch):
    from greptimedb_tpu.query import stats as qstats

    fe, ref = topo
    rng = np.random.default_rng(SEED + batch * 104729)
    pushed = 0
    for _ in range(PER_BATCH):
        q, _expect_push = _random_query(rng)
        want = ref.sql(q).rows()
        with qstats.collect() as collected:
            got = fe.sql(q).rows()
        assert _match(got, want), (
            f"dist != standalone for: {q}\n{got}\nvs\n{want}"
        )
        if collected.counters.get("dist_partial_datanodes", 0) > 0:
            pushed += 1
    # the fuzz must actually exercise the partial-plan merge, not the
    # data-shipping fallback
    assert pushed >= PER_BATCH * 2 // 3, pushed
