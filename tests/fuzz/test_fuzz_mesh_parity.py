"""Sharded-vs-single-device parity fuzz (ISSUE 7 satellite): random
decomposable aggregate / RANGE / PromQL (incl. topk) queries run on a
forced 8-device CPU mesh (conftest pins
XLA_FLAGS=--xla_force_host_platform_device_count=8) and on one device,
asserting BIT-IDENTICAL results. The blocked exact folds
(parallel/mesh.FOLD_BLOCKS, parallel/dist.LocalFoldCtx/ShardFoldCtx)
promise the same f32 additions in the same order on every mesh size —
this fuzz is that contract's enforcement.

Deterministic by default (seeded); set GREPTIMEDB_TPU_FUZZ_SEED to
explore, GREPTIMEDB_TPU_FUZZ_ITERS to lengthen. Defaults generate
4 batches x 25 = 100 compared queries. The query space is sampled from
a bounded shape grid so XLA compiles amortise across iterations.
"""

import os

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.parallel import mesh as M
from greptimedb_tpu.query import stats as qstats
from greptimedb_tpu.query.executor import QueryEngine
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql.parser import parse_sql

SEED = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_SEED", "20260803"))
BATCHES = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_ITERS", "2"))
PER_BATCH = 20

# tiny test grids: force the replicate-vs-shard planner to shard so the
# shard_map programs actually execute (prod defaults gate on 4096 series)
FORCE_SHARD = M.MeshOptions(shard_min_series=1, shard_min_rows=1)
# kernel leg (ISSUE 17): same sharding, plus the Pallas ring/merge
# kernel programs forced on (interpret mode on this CPU platform, so
# the real kernel bodies execute) with thresholds dropped to the floor
FORCE_KERNEL = M.MeshOptions(shard_min_series=1, shard_min_rows=1,
                             pallas_kernels="on", pallas_min_series=1,
                             pallas_min_rows=1)

ROW_AGGS = ["count", "sum", "min", "max", "avg",
            "first_value", "last_value"]
RANGE_AGGS = ROW_AGGS + ["stddev_samp", "var_pop"]
PROM_AGG_OPS = ["sum", "avg", "count", "min", "max", "stddev", "stdvar"]
PROM_FNS = ["rate", "increase", "delta", "sum_over_time",
            "avg_over_time", "max_over_time", "min_over_time"]


@pytest.fixture(scope="module")
def sql_setup(tmp_path_factory):
    rng = np.random.default_rng(SEED)
    inst = Standalone(str(tmp_path_factory.mktemp("mesh_parity")))
    inst.execute_sql(
        "create table fz (ts timestamp time index, host string primary "
        "key, u double, v double)"
    )
    tab = inst.catalog.table("public", "fz")
    n_hosts, t = 24, 120
    ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
    hosts = np.repeat(
        [f"h{i:02d}" for i in range(n_hosts)], t
    ).astype(object)
    u = rng.random(n_hosts * t) * 200 - 100
    v = rng.random(n_hosts * t) * 50
    tab.write({"host": hosts}, ts, {"u": u, "v": v})
    e1 = QueryEngine(prefer_device=True)
    em = QueryEngine(prefer_device=True, mesh=M.make_mesh(),
                     mesh_opts=FORCE_SHARD)
    ek = QueryEngine(prefer_device=True, mesh=M.make_mesh(),
                     mesh_opts=FORCE_KERNEL)
    yield inst, e1, em, ek
    inst.close()


def _run(engine, inst, sql):
    stmt = parse_sql(sql)[0]
    plan, table = inst.plan(stmt, QueryContext())
    return engine.execute(plan, table)


def _exact(ra, rb, q):
    assert ra.names == rb.names, q
    assert ra.num_rows == rb.num_rows, (
        f"row count differs for: {q} ({ra.num_rows} vs {rb.num_rows})"
    )
    for i, name in enumerate(ra.names):
        a, b = np.asarray(ra.cols[i].values), np.asarray(rb.cols[i].values)
        if a.dtype == object or b.dtype == object:
            ok = all(
                (x is None and y is None) or x == y
                for x, y in zip(a.tolist(), b.tolist())
            )
            assert ok, f"column {name} differs for: {q}\n{a}\nvs\n{b}"
        else:
            assert np.array_equal(a, b, equal_nan=True), (
                f"column {name} not bit-identical for: {q}\n{a}\nvs\n{b}"
            )


def _random_sql(rng) -> str:
    """Decomposable aggregate / RANGE shapes over a bounded grid of
    static program specs (ranges, aligns, group keys) so compiles
    amortise while ops and predicates stay random."""
    f = rng.choice(["u", "v"])
    if rng.random() < 0.5:
        # RANGE query: grid path, series-sharded cell states
        agg = rng.choice(RANGE_AGGS)
        rng_s, align = rng.choice([(60, 60), (120, 60), (120, 120)])
        by = rng.choice(["BY (host)", "BY ()"])
        order = "ts, host" if "host" in by else "ts"
        where = ""
        if rng.random() < 0.3:
            # cell-edge-aligned ts bound keeps the device partial valid
            lo = int(rng.integers(1, 8)) * 120_000
            where = f" WHERE ts >= {lo}"
        extra = ""
        if rng.random() < 0.4:
            agg2 = rng.choice(["count", "sum", "max"])
            extra = f", {agg2}({f}) RANGE '{rng_s}s'"
        return (
            f"SELECT ts{', host' if 'host' in by else ''}, "
            f"{agg}({f}) RANGE '{rng_s}s'{extra} FROM fz{where} "
            f"ALIGN '{align}s' {by} ORDER BY {order}"
        )
    # plain GROUP BY: row path, fused sharded reduce
    agg = rng.choice(ROW_AGGS)
    agg2 = rng.choice(["count", "sum", "avg"])
    keyed = rng.random() < 0.7
    where = ""
    if rng.random() < 0.3:
        where = f" WHERE {f} > {rng.random() * 40 - 20:.2f}"
    if keyed:
        return (
            f"SELECT host, {agg}({f}) AS a, {agg2}(v) AS b FROM fz"
            f"{where} GROUP BY host ORDER BY host"
        )
    return f"SELECT {agg}({f}) AS a, {agg2}(v) AS b FROM fz{where}"


@pytest.mark.parametrize("batch", range(BATCHES))
def test_mesh_parity_fuzz_sql(sql_setup, batch):
    inst, e1, em, _ek = sql_setup
    rng = np.random.default_rng(SEED + batch * 104729)
    sharded = 0
    for _ in range(PER_BATCH):
        q = _random_sql(rng)
        r1 = _run(e1, inst, q)
        with qstats.collect() as collected:
            rm = _run(em, inst, q)
        _exact(r1, rm, q)
        if collected.counters.get("mesh_devices", 0) > 1:
            sharded += 1
    # the fuzz must exercise the shard_map programs, not just the
    # replicate fallback
    assert sharded >= PER_BATCH * 2 // 3, sharded


@pytest.mark.parametrize("batch", range(BATCHES))
def test_mesh_parity_fuzz_sql_kernels(sql_setup, batch):
    """Kernel-path leg (ISSUE 17 satellite): the Pallas ring programs
    (interpret mode — real kernel bodies on the forced 8-device CPU
    mesh) stay bit-identical to the single-device engine on the same
    random query stream, and actually take the kernel path."""
    inst, e1, _em, ek = sql_setup
    rng = np.random.default_rng(SEED + batch * 104729)
    kernel_hits = 0
    for _ in range(PER_BATCH):
        q = _random_sql(rng)
        r1 = _run(e1, inst, q)
        with qstats.collect() as collected:
            rk = _run(ek, inst, q)
        _exact(r1, rk, q)
        if any(k.startswith("mesh_kernel_") and v.startswith("pallas(")
               for k, v in collected.notes.items()):
            kernel_hits += 1
    # the leg must exercise the Pallas programs, not the XLA fallback
    assert kernel_hits >= PER_BATCH * 2 // 3, kernel_hits


# ----------------------------------------------------------------------
# PromQL: rate/aggregate + topk over the selector-grid fast path
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def prom_setup(tmp_path_factory):
    def build(home, mesh, opts=FORCE_SHARD):
        rng = np.random.default_rng(SEED)  # identical data all builds
        inst = Standalone(str(home), prefer_device=True, mesh=mesh,
                          mesh_opts=None if mesh is None else opts,
                          warm_start=False)
        inst.execute_sql(
            "create table http_requests (ts timestamp time index, "
            "host string primary key, dc string primary key, "
            "greptime_value double)"
        )
        tab = inst.catalog.table("public", "http_requests")
        n_hosts, t = 24, 120
        ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
        hosts = np.repeat(
            [f"h{k:02d}" for k in range(n_hosts)], t
        ).astype(object)
        dcs = np.repeat(
            [f"dc{k % 3}" for k in range(n_hosts)], t
        ).astype(object)
        vals = np.cumsum(rng.random(n_hosts * t), 0)
        tab.write({"host": hosts, "dc": dcs}, ts,
                  {"greptime_value": vals})
        return inst

    tmp = tmp_path_factory.mktemp("mesh_parity_prom")
    i1 = build(tmp / "single", None)
    im = build(tmp / "mesh", M.make_mesh())
    ik = build(tmp / "kern", M.make_mesh(), FORCE_KERNEL)
    yield i1, im, ik
    from greptimedb_tpu.promql import fast as F

    F.invalidate_cache()
    i1.close()
    im.close()
    ik.close()


def _random_promql(rng) -> str:
    fn = rng.choice(PROM_FNS)
    sel = "http_requests[2m]"
    if rng.random() < 0.3:
        # topk/bottomk: the dist_topk per-shard select + reselect path
        op = rng.choice(["topk", "bottomk"])
        k = int(rng.choice([3, 7]))
        return f"{op}({k}, {fn}({sel}))"
    op = rng.choice(PROM_AGG_OPS)
    by = rng.choice(["by (dc) ", ""])
    return f"{op} {by}({fn}({sel}))"


def _prom_exact(queries, rs1, rs2, tag=""):
    for q, r1, rm in zip(queries, rs1, rs2):
        l1 = [frozenset(lb.items()) for lb in r1.labels]
        lm = [frozenset(lb.items()) for lb in rm.labels]
        assert l1 == lm, f"labels differ for{tag}: {q}"
        assert (r1.present == rm.present).all(), \
            f"presence differs{tag}: {q}"
        a = np.where(r1.present, r1.values, 0.0)
        b = np.where(rm.present, rm.values, 0.0)
        assert np.array_equal(a, b, equal_nan=True), (
            f"values not bit-identical for{tag}: {q}\n{a}\nvs\n{b}"
        )


@pytest.mark.slow  # tier-1 budget: SQL fuzz twins keep mesh-parity gated
def test_mesh_parity_fuzz_promql(prom_setup):
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine
    from greptimedb_tpu.telemetry.metrics import global_registry

    i1, im, ik = prom_setup
    rng = np.random.default_rng(SEED + 7919)
    queries = [_random_promql(rng) for _ in range(PER_BATCH)]
    t0, t1, step = 0, 119 * 10_000, 60_000

    def run_all(inst):
        F.invalidate_cache()
        eng = PromEngine(inst)
        out = []
        for q in queries:
            r, _ = eng.query_range(q, t0, t1, step)
            out.append(r)
        return out

    rs1 = run_all(i1)
    rsm = run_all(im)
    # the mesh build's grid really is series-sharded over 8 devices
    entry = next(iter(F._CACHE._entries.values()))
    assert entry.mesh is not None
    assert len(entry.vals.devices()) == 8
    _prom_exact(queries, rs1, rsm)
    # kernel leg (ISSUE 17 satellite): the ring topk merge + ring fold
    # programs, interpret mode, same stream — still bit-identical, and
    # the topk queries really took the Pallas path
    ctr = global_registry.counter(
        "gtpu_mesh_queries_total",
        "Mesh execution decisions by mode/reason/site",
        labels=("kind", "mode", "reason"),
    ).labels("topk_kernel", "pallas", "ring_topk")
    before = ctr.value
    rsk = run_all(ik)
    _prom_exact(queries, rs1, rsk, tag=" (kernel)")
    n_topk = sum(1 for q in queries if q.startswith(("topk", "bottomk")))
    assert n_topk > 0
    assert ctr.value - before >= n_topk
