"""Property-based fuzz tier (VERDICT row 31; ref tests-fuzz/):
randomized DDL/ingest/query programs run against the engine with
metamorphic oracles instead of golden outputs:

- robustness: any failure must surface as a GreptimeError (never an
  internal TypeError/IndexError/AssertionError);
- device/host equivalence: RANGE queries agree between the two paths;
- dedup idempotence: writing the same rows twice changes nothing;
- durability: close + reopen replays to identical query results.

Deterministic by default (seeded); set GREPTIMEDB_TPU_FUZZ_SEED to
explore, GREPTIMEDB_TPU_FUZZ_ITERS to lengthen.
"""

import os

import numpy as np
import pytest

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.query.executor import QueryEngine

SEED = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_SEED", "20260730"))
ITERS = int(os.environ.get("GREPTIMEDB_TPU_FUZZ_ITERS", "12"))

AGGS = ["avg", "sum", "min", "max", "count", "stddev",
        "first_value", "last_value"]
FILLS = ["", " FILL NULL", " FILL PREV", " FILL 0"]


def _mk_schema(rng):
    n_tags = int(rng.integers(1, 3))
    n_fields = int(rng.integers(1, 4))
    tags = [f"t{i}" for i in range(n_tags)]
    fields = [f"f{i}" for i in range(n_fields)]
    return tags, fields


def _create(inst, tags, fields):
    cols = ", ".join(
        [f"{t} STRING" for t in tags]
        + [f"{f} DOUBLE" for f in fields]
        + ["ts TIMESTAMP TIME INDEX"]
    )
    pk = ", ".join(tags)
    inst.sql(f"CREATE TABLE fz ({cols}, PRIMARY KEY ({pk}))")


def _ingest(inst, rng, tags, fields, n_rows):
    card = int(rng.integers(2, 6))
    parts = []
    for _ in range(n_rows):
        tvals = [f"'v{int(rng.integers(0, card))}'" for _ in tags]
        fvals = []
        for _ in fields:
            if rng.random() < 0.1:
                fvals.append("NULL")
            else:
                fvals.append(f"{rng.random() * 200 - 100:.4f}")
        ts = int(rng.integers(0, 50)) * 1000
        parts.append(f"({', '.join(tvals + fvals)}, {ts})")
    cols = ", ".join(tags + fields + ["ts"])
    sql = f"INSERT INTO fz ({cols}) VALUES " + ", ".join(parts)
    inst.sql(sql)
    return sql


def _random_range_query(rng, tags, fields) -> str:
    agg = rng.choice(AGGS)
    field = rng.choice(fields)
    arg = "*" if agg == "count" and rng.random() < 0.3 else field
    if agg in ("first_value", "last_value"):
        item = f"{agg}({arg}) RANGE '{int(rng.integers(1, 4)) * 5}s'"
    else:
        item = f"{agg}({arg}) RANGE '{int(rng.integers(1, 4)) * 5}s'"
    by = ""
    sel_keys = "ts"
    if rng.random() < 0.7:
        k = rng.choice(tags)
        by = f" BY ({k})"
        sel_keys = f"ts, {k}"
    else:
        by = " BY ()"
    fill = rng.choice(FILLS)
    align = int(rng.integers(1, 3)) * 5
    where = ""
    if rng.random() < 0.3:
        where = f" WHERE {rng.choice(tags)} != 'v0'"
    return (
        f"SELECT {sel_keys}, {item}{fill} FROM fz{where} "
        f"ALIGN '{align}s'{by} ORDER BY {sel_keys}"
    )


def _random_plain_query(rng, tags, fields) -> str:
    agg = rng.choice(["avg", "sum", "min", "max", "count"])
    field = rng.choice(fields)
    k = rng.choice(tags)
    having = " HAVING c >= 0" if rng.random() < 0.2 else ""
    return (
        f"SELECT {k}, {agg}({field}) AS a, count(*) AS c FROM fz "
        f"GROUP BY {k}{having} ORDER BY {k}"
    )


def _rows_or_fail(inst, q):
    try:
        return inst.sql(q).rows()
    except GreptimeError:
        return None   # rejected cleanly: acceptable
    except Exception as e:  # noqa: BLE001 - the oracle
        raise AssertionError(
            f"non-Greptime error {type(e).__name__}: {e}\nquery: {q}"
        ) from e


@pytest.mark.parametrize("case", range(ITERS))
def test_fuzz_program(tmp_path, case):
    rng = np.random.default_rng(SEED + case * 7919)
    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    try:
        tags, fields = _mk_schema(rng)
        _create(inst, tags, fields)
        ins_sqls = []
        for _ in range(int(rng.integers(1, 4))):
            ins_sqls.append(
                _ingest(inst, rng, tags, fields, int(rng.integers(5, 60)))
            )

        queries = (
            [_random_range_query(rng, tags, fields) for _ in range(4)]
            + [_random_plain_query(rng, tags, fields) for _ in range(2)]
        )
        # host vs device equivalence
        host_res = {}
        inst.query_engine = QueryEngine(prefer_device=False)
        for q in queries:
            host_res[q] = _rows_or_fail(inst, q)
        inst.query_engine = QueryEngine(prefer_device=True)
        inst.query_engine.persist_device_cache = False
        for q in queries:
            got = _rows_or_fail(inst, q)
            want = host_res[q]
            assert _match(got, want), (
                f"device != host for: {q}\n{got}\nvs\n{want}"
            )

        # dedup idempotence: re-writing identical rows must not change
        # any query result (last-write-wins on (series, ts))
        for s in ins_sqls:
            inst.sql(s)
        inst.query_engine = QueryEngine(prefer_device=False)
        for q in queries:
            got = _rows_or_fail(inst, q)
            assert _match(got, host_res[q]), f"dedup changed: {q}"

        # durability: reopen replays WAL to the same answers
        inst.close()
        inst = Standalone(str(tmp_path / "data"), warm_start=False)
        inst.query_engine = QueryEngine(prefer_device=False)
        for q in queries:
            got = _rows_or_fail(inst, q)
            assert _match(got, host_res[q]), f"replay changed: {q}"
    finally:
        inst.close()


def _match(a, b) -> bool:
    if a is None or b is None:
        return (a is None) == (b is None)
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if va is None or vb is None:
                if (va is None) != (vb is None):
                    return False
            elif isinstance(va, float) or isinstance(vb, float):
                if not np.isclose(float(va), float(vb),
                                  rtol=2e-4, atol=1e-3, equal_nan=True):
                    return False
            elif va != vb:
                return False
    return True
