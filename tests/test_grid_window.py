"""Grid scatter + window kernels vs numpy references."""

import numpy as np
import jax.numpy as jnp
import pytest

from greptimedb_tpu.ops import grid as G
from greptimedb_tpu.ops import window as W


def make_series(rng, s=5, points=200, t0=1_700_000_000_000, interval=10_000,
                drop=0.15):
    """Irregular per-series samples: (sid, ts, val) sorted by (sid, ts)."""
    rows = []
    for sid in range(s):
        ts = t0 + np.arange(points) * interval
        keep = rng.random(points) > drop
        ts = ts[keep]
        vals = np.cumsum(rng.random(keep.sum()) * 5)  # counter-ish
        for t, v in zip(ts, vals):
            rows.append((sid, t, v))
    rows.sort()
    sid = np.array([r[0] for r in rows], dtype=np.int32)
    ts = np.array([r[1] for r in rows], dtype=np.int64)
    val = np.array([r[2] for r in rows], dtype=np.float64)
    return sid, ts, val


def test_gridspec_cell_convention():
    spec = G.GridSpec.build(t0=1000, res=10, num_cells=100)
    # sample exactly at a cell boundary belongs to the cell ending there
    assert spec.cell_of(1010) == 1
    assert spec.cell_of(1011) == 2
    assert spec.cell_of(1020) == 2
    assert spec.cell_of(1000) == 0
    assert spec.cell_of(1001) == 1


def test_gridify_last_wins(rng):
    spec = G.GridSpec.build(t0=0, res=10, num_cells=10)
    # two samples in the same cell: later row index wins
    sid = np.array([0, 0], dtype=np.int32)
    ts = np.array([13, 17], dtype=np.int64)
    cell = spec.cell_of(ts).astype(np.int32)
    tsr = spec.device_ts(ts)
    vals, has, tsg = G.gridify(
        jnp.array(sid), jnp.array(cell), jnp.array(tsr),
        jnp.array([1.0, 2.0]), jnp.array([True, True]), 1, 10,
    )
    assert np.asarray(has)[0, 2]
    assert np.asarray(vals)[0, 2] == 2.0
    assert np.asarray(tsg)[0, 2] == 17


def test_gridify_roundtrip(rng):
    sid, ts, val = make_series(rng)
    t0 = int(ts.min()) - 1
    res = 10_000
    num_cells = int((ts.max() - t0 + res - 1) // res) + 1
    spec = G.GridSpec.build(t0, res, num_cells)
    cell = spec.cell_of(ts).astype(np.int32)
    tsr = spec.device_ts(ts)
    mask = np.ones(len(sid), dtype=bool)
    vals, has, tsg = G.gridify(
        jnp.array(sid), jnp.array(cell), jnp.array(tsr), jnp.array(val),
        jnp.array(mask), 5, num_cells,
    )
    vals, has, tsg = map(np.asarray, (vals, has, tsg))
    assert has.sum() == len(sid)  # no collisions at this res
    for i in rng.choice(len(sid), 50):
        s, c = sid[i], cell[i]
        assert has[s, c]
        assert vals[s, c] == val[i]
        assert tsg[s, c] == tsr[i]


@pytest.fixture
def gridded(rng):
    sid, ts, val = make_series(rng)
    start = int(ts.min()) + 300_000
    end = start + 1_000_000
    step, rng_ms = 60_000, 300_000
    spec, windows = W.plan_grid_and_windows(start, end, step, rng_ms,
                                            data_interval_ms=10_000)
    cell = spec.cell_of(ts).astype(np.int32)
    tsr = spec.device_ts(ts)
    mask = np.ones(len(sid), dtype=bool)
    vals, has, tsg = G.gridify(
        jnp.array(sid), jnp.array(cell), jnp.array(tsr), jnp.array(val),
        jnp.array(mask), 5, spec.num_cells,
    )
    return (sid, ts, val), spec, windows, (vals, has, tsg)


def window_samples(rows, spec, windows, s, j):
    """Reference: samples of series s with ts in (t_end - range, t_end]."""
    sid, ts, val = rows
    t_end_ms = spec.t0 + int(windows.t_end[j]) * spec.unit
    t_lo_ms = t_end_ms - windows.range_ticks * spec.unit
    sel = (sid == s) & (ts > t_lo_ms) & (ts <= t_end_ms)
    return ts[sel], val[sel]


def test_window_count_sum_avg(gridded):
    rows, spec, windows, (vals, has, tsg) = gridded
    lo, hi = jnp.array(windows.lo), jnp.array(windows.hi)
    cnt = np.asarray(W.window_count(has, lo, hi))
    ssum, _ = W.window_sum(vals, has, lo, hi)
    ssum = np.asarray(ssum)
    for s in range(5):
        for j in range(0, windows.num_steps, 3):
            wts, wv = window_samples(rows, spec, windows, s, j)
            assert cnt[s, j] == len(wts), (s, j)
            np.testing.assert_allclose(ssum[s, j], wv.sum(), rtol=1e-12)


def test_window_first_last(gridded):
    rows, spec, windows, (vals, has, tsg) = gridded
    lo, hi = jnp.array(windows.lo), jnp.array(windows.hi)
    lv, lt, lp = W.window_last(vals, has, tsg, lo, hi)
    fv, ft, fp = W.window_first(vals, has, tsg, lo, hi)
    lv, lp, fv, fp = map(np.asarray, (lv, lp, fv, fp))
    for s in range(5):
        for j in range(windows.num_steps):
            wts, wv = window_samples(rows, spec, windows, s, j)
            if len(wts):
                assert lp[s, j] and fp[s, j]
                assert lv[s, j] == wv[-1]
                assert fv[s, j] == wv[0]
            else:
                assert not lp[s, j] and not fp[s, j]


def test_window_minmax_quantile(gridded):
    rows, spec, windows, (vals, has, tsg) = gridded
    hi = jnp.array(windows.hi)
    l_cells = windows.num_cells_per_window
    mn, mp = W.window_minmax(vals, has, tsg, hi, l_cells, "min")
    mx, _ = W.window_minmax(vals, has, tsg, hi, l_cells, "max")
    md, qp = W.window_quantile(vals, has, tsg, hi, l_cells, 0.5)
    mn, mx, md, mp = map(np.asarray, (mn, mx, md, mp))
    for s in range(5):
        for j in range(0, windows.num_steps, 4):
            wts, wv = window_samples(rows, spec, windows, s, j)
            if len(wts):
                np.testing.assert_allclose(mn[s, j], wv.min(), rtol=1e-12)
                np.testing.assert_allclose(mx[s, j], wv.max(), rtol=1e-12)
                np.testing.assert_allclose(
                    md[s, j], np.quantile(wv, 0.5), rtol=1e-9
                )


def test_instant_lookback(gridded):
    rows, spec, windows, (vals, has, tsg) = gridded
    sid, ts, val = rows
    hi = jnp.array(windows.hi)
    t_end = jnp.array(windows.t_end)
    lookback = 300_000 // spec.unit
    v, p = W.instant_lookback(vals, has, tsg, hi, t_end, lookback)
    v, p = np.asarray(v), np.asarray(p)
    for s in range(5):
        for j in range(windows.num_steps):
            t_end_ms = spec.t0 + int(windows.t_end[j]) * spec.unit
            sel = (sid == s) & (ts <= t_end_ms) & (ts > t_end_ms - 300_000)
            if sel.any():
                assert p[s, j]
                np.testing.assert_allclose(v[s, j], val[sel][-1], rtol=1e-12)
            else:
                assert not p[s, j]


def test_window_rows_preceding_frames(tmp_path):
    """ROWS BETWEEN k PRECEDING AND CURRENT ROW (VERDICT r3 weak #6)."""
    from greptimedb_tpu.instance import Standalone

    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table w (ts timestamp time index, g string "
            "primary key, v double)"
        )
        inst.execute_sql(
            "insert into w (ts, g, v) values (1000,'a',1),(2000,'a',2),"
            "(3000,'a',3),(4000,'a',4),(1000,'b',10),(2000,'b',20)"
        )
        r = inst.sql(
            "select g, ts, sum(v) over (partition by g order by ts "
            "rows between 1 preceding and current row) as s, "
            "avg(v) over (partition by g order by ts "
            "rows between 1 preceding and current row) as a, "
            "count(v) over (partition by g order by ts "
            "rows between 2 preceding and current row) as c "
            "from w order by g, ts"
        ).rows()
        assert [x[2] for x in r] == [1.0, 3.0, 5.0, 7.0, 10.0, 30.0]
        assert [x[3] for x in r] == [1.0, 1.5, 2.5, 3.5, 10.0, 15.0]
        assert [x[4] for x in r] == [1, 2, 3, 3, 1, 2]
        r = inst.sql(
            "select max(v) over (partition by g order by ts "
            "rows between 1 preceding and current row) as m "
            "from w order by g, ts"
        ).rows()
        assert [x[0] for x in r] == [1.0, 2.0, 3.0, 4.0, 10.0, 20.0]
        # shorthand frame: 'ROWS k PRECEDING' == BETWEEN k PRECEDING AND
        # CURRENT ROW (ADVICE r4)
        r = inst.sql(
            "select sum(v) over (partition by g order by ts "
            "rows 1 preceding) as s from w order by g, ts"
        ).rows()
        assert [x[0] for x in r] == [1.0, 3.0, 5.0, 7.0, 10.0, 30.0]
        r = inst.sql(
            "select sum(v) over (partition by g order by ts "
            "rows unbounded preceding) as s from w order by g, ts"
        ).rows()
        assert [x[0] for x in r] == [1.0, 3.0, 6.0, 10.0, 10.0, 30.0]
    finally:
        inst.close()


def test_window_device_path_matches_host(tmp_path, monkeypatch, rng):
    """Large-partition running aggregates run the segmented scans on
    the device; results must equal the host path exactly."""
    from greptimedb_tpu import query
    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.query import stats as qstats
    from greptimedb_tpu.query import window_fns as W

    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table w (ts timestamp time index, g string "
            "primary key, v double)"
        )
        tab = inst.catalog.table("public", "w")
        n = 4000
        ts = np.tile(np.arange(n // 4) * 1000, 4).astype(np.int64)
        gs = np.repeat([f"g{i}" for i in range(4)], n // 4).astype(object)
        tab.write({"g": gs}, ts, {"v": rng.random(n) * 100})
        q = ("select g, ts, sum(v) over (partition by g order by ts) "
             "as s, min(v) over (partition by g order by ts) as m, "
             "count(v) over (partition by g order by ts) as c "
             "from w order by g, ts")
        host = inst.sql(q).rows()
        monkeypatch.setattr(W, "DEVICE_THRESHOLD", 100)
        with qstats.collect() as st:
            dev = inst.sql(q).rows()
        assert st.notes.get("exec_path_window") == "device"
        assert len(host) == len(dev)
        for h, d in zip(host, dev):
            assert h[0] == d[0] and h[1] == d[1]
            np.testing.assert_allclose(h[2], d[2], rtol=1e-12)
            assert h[3] == d[3] and h[4] == d[4]
    finally:
        inst.close()


def test_window_device_path_without_x64(tmp_path, monkeypatch, rng):
    """Real-TPU configuration (no x64): running aggregates still run on
    device via Neumaier-compensated / two-float f32 segmented scans and
    match host f64 within tolerance (VERDICT r4 #5)."""
    import jax

    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.query import stats as qstats
    from greptimedb_tpu.query import window_fns as W

    inst = Standalone(str(tmp_path / "d"), prefer_device=False,
                      warm_start=False)
    try:
        inst.execute_sql(
            "create table w (ts timestamp time index, g string "
            "primary key, v double)"
        )
        tab = inst.catalog.table("public", "w")
        n = 8000
        ts = np.tile(np.arange(n // 4) * 1000, 4).astype(np.int64)
        gs = np.repeat([f"g{i}" for i in range(4)], n // 4).astype(object)
        # large magnitudes + tiny increments: a raw f32 cumsum would
        # lose the small terms; the compensated scan must not
        vals = rng.random(n) * 1e6 + rng.random(n) * 1e-3
        tab.write({"g": gs}, ts, {"v": vals})
        q = ("select g, ts, sum(v) over (partition by g order by ts) "
             "as s, max(v) over (partition by g order by ts) as m, "
             "count(v) over (partition by g order by ts) as c "
             "from w order by g, ts")
        host = inst.sql(q).rows()
        monkeypatch.setattr(W, "DEVICE_THRESHOLD", 100)
        saved_x64 = bool(jax.config.read("jax_enable_x64"))
        jax.config.update("jax_enable_x64", False)
        try:
            with qstats.collect() as st:
                dev = inst.sql(q).rows()
        finally:
            jax.config.update("jax_enable_x64", saved_x64)
        assert st.notes.get("exec_path_window") == "device"
        assert len(host) == len(dev)
        for h, d in zip(host, dev):
            assert h[0] == d[0] and h[1] == d[1]
            np.testing.assert_allclose(h[2], d[2], rtol=1e-9)
            # two-float pairs carry 48 mantissa bits vs f64's 53
            np.testing.assert_allclose(h[3], d[3], rtol=1e-12)
            assert h[4] == d[4]
    finally:
        inst.close()


def test_interval_column_type(tmp_path):
    """INTERVAL as a first-class column type (VERDICT r3 missing #5):
    DDL, ingest, arithmetic with timestamps, flush + restart."""
    from greptimedb_tpu.instance import Standalone

    home = str(tmp_path / "d")
    inst = Standalone(home, prefer_device=False, warm_start=False)
    inst.execute_sql(
        "create table iv (ts timestamp time index, d interval, v double)"
    )
    inst.execute_sql(
        "insert into iv (ts, d, v) values "
        "(1000, INTERVAL '1 hour', 1.0), "
        "(2000, INTERVAL '90 minutes', 2.0)"
    )
    assert inst.sql("select d from iv order by ts").rows() == [
        [3600000], [5400000]
    ]
    assert inst.sql("select ts + d from iv order by ts").rows() == [
        [3601000], [5402000]
    ]
    assert inst.sql("select INTERVAL '1 hour' + ts from iv "
                    "order by ts").rows() == [[3601000], [3602000]]
    ddl = inst.sql("show create table iv").rows()[0][1]
    assert "`d` INTERVAL" in ddl
    inst.execute_sql("admin flush_table('iv')")
    inst.close()
    # restart: the type survives the SST + catalog round trip
    inst2 = Standalone(home, prefer_device=False, warm_start=False)
    try:
        assert inst2.sql("select d, v from iv order by ts").rows() == [
            [3600000, 1.0], [5400000, 2.0]
        ]
        t = inst2.catalog.table("public", "iv")
        assert t.schema.column("d").data_type.is_interval()
    finally:
        inst2.close()


def test_interval_duration_wire_normalization():
    """Arrow duration columns in ANY unit land as int64 milliseconds
    (the INTERVAL type contract) — a duration('s') 5 is 5000 ms."""
    import pyarrow as pa

    from greptimedb_tpu.datatypes.batch import HostColumn

    hc = HostColumn.from_arrow(
        "d", pa.array([5, None, 2], pa.duration("s"))
    )
    assert hc.values.dtype == np.int64
    assert list(hc.values[[0, 2]]) == [5000, 2000]
    assert list(hc.valid_mask) == [True, False, True]
    hc2 = HostColumn.from_arrow(
        "d", pa.array([7], pa.duration("ms"))
    )
    assert hc2.values.dtype == np.int64 and hc2.values[0] == 7
