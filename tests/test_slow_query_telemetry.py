"""Slow-query log, SHOW CREATE FLOW, anonymous telemetry reporter.

Reference: StatementStatistics slow-query wiring (src/cmd/src/
standalone.rs:570), SHOW CREATE FLOW (src/sql/src/parser.rs), and
src/common/greptimedb-telemetry/src/lib.rs.
"""

import http.server
import json
import threading

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.telemetry.report import TelemetryTask, install_uuid
from greptimedb_tpu.telemetry.slow_query import SlowQueryLog


@pytest.fixture()
def inst(tmp_path):
    inst = Standalone(str(tmp_path / "data"), prefer_device=False,
                      warm_start=False)
    yield inst
    inst.close()


def test_slow_query_recorded(inst):
    inst.slow_query_log = SlowQueryLog(threshold_s=0.0)
    inst.execute_sql(
        "create table t (ts timestamp time index, v double)"
    )
    inst.sql("select count(v) from t")
    entries = inst.slow_query_log.entries()
    assert any("select count(v)" in e["query"] for e in entries)
    r = inst.sql("select query, cost_time_ms from information_schema.slow_queries")
    assert r.num_rows >= 1
    # threshold filters
    log = SlowQueryLog(threshold_s=10.0)
    log.maybe_record("fast", 0.001)
    assert log.entries() == []
    log.maybe_record("slow", 11.0, db="public")
    assert log.entries()[0]["query"] == "slow"
    # disabled log records nothing
    off = SlowQueryLog(enable=False, threshold_s=0.0)
    off.maybe_record("x", 99.0)
    assert off.entries() == []


def test_show_create_flow(inst):
    inst.enable_flows(tick_interval_s=3600.0)
    inst.execute_sql(
        "create table src (ts timestamp time index, host string primary "
        "key, v double)"
    )
    inst.execute_sql(
        "create flow f1 sink to agg_out as "
        "select host, sum(v) from src group by host"
    )
    r = inst.sql("show create flow f1")
    assert r.names == ["Flow", "Create Flow"]
    text = str(r.cols[1].values[0]).lower()
    assert "create flow" in text and "sink to" in text
    from greptimedb_tpu.errors import TableNotFoundError

    with pytest.raises(TableNotFoundError):
        inst.sql("show create flow nope")


def test_install_uuid_stable(tmp_path):
    a = install_uuid(str(tmp_path))
    b = install_uuid(str(tmp_path))
    assert a == b and len(a) == 36


def test_telemetry_report_roundtrip(tmp_path):
    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        task = TelemetryTask(
            str(tmp_path),
            endpoint=f"http://127.0.0.1:{httpd.server_address[1]}/report",
            mode="standalone",
        )
        assert task.report_once()
        assert task.reports_sent == 1
        payload = received[0]
        assert payload["uuid"] == install_uuid(str(tmp_path))
        assert payload["mode"] == "standalone"
        assert payload["version"]
    finally:
        httpd.shutdown()


def test_telemetry_failure_is_silent(tmp_path):
    task = TelemetryTask(str(tmp_path),
                         endpoint="http://127.0.0.1:1/nope")
    assert task.report_once() is False
    assert task.reports_sent == 0
