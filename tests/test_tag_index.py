"""Secondary tag index units (index/): postings vs the registry
oracle, version-validated result caching, incremental maintenance,
device-plane parity + census, SST sid pruning, matcher memoization."""

import re

import numpy as np
import pytest

from greptimedb_tpu import index as _index
from greptimedb_tpu.index import device_plane
from greptimedb_tpu.index.tag_index import TagIndex
from greptimedb_tpu.storage.series import SeriesRegistry
from greptimedb_tpu.telemetry.metrics import global_registry


def _make_registry(n=2000, hosts=16, regions=5, seed=0):
    rng = np.random.default_rng(seed)
    reg = SeriesRegistry(["host", "region"])
    cols = [
        np.asarray([f"h{v}" for v in rng.integers(0, hosts, n)], object),
        np.asarray([f"r{v}" for v in rng.integers(0, regions, n)],
                   object),
    ]
    reg.intern_rows(cols)
    return reg


CASES = [
    [("host", "eq", "h3")],
    [("host", "in", ["h1", "h5", "h7"])],
    [("host", "ne", "h0")],
    [("host", "re", re.compile(r"h1[12]?"))],
    [("host", "nre", re.compile(r"h[0-4]"))],
    [("host", "eq", "h2"), ("region", "eq", "r1")],
    [("host", "ne", ""), ("region", "in", ["r0", "r4"])],
    [("missing", "eq", "x")],          # absent tag: constant verdict
    [("missing", "eq", "")],           # matches everything (empty tag)
    [("host", "eq", "nosuchvalue")],
    [],                                # no matchers
]


def test_match_sids_bit_identical_to_registry():
    reg = _make_registry()
    ix = TagIndex(reg)
    for m in CASES:
        want = reg.match_sids(m) if m else np.arange(
            reg.num_series, dtype=np.int32)
        got = _index.match_sids(reg, m)
        np.testing.assert_array_equal(got, want), m
        assert got.dtype == want.dtype
        if m:
            np.testing.assert_array_equal(ix.match_sids(m), want)


def test_match_sids_fuzz_against_oracle():
    rng = np.random.default_rng(7)
    reg = _make_registry(n=5000, hosts=40, regions=9, seed=1)
    ix = TagIndex(reg)
    ops = ["eq", "ne", "in", "nin", "re", "nre"]
    for _ in range(150):
        m = []
        for _ in range(rng.integers(1, 4)):
            tag = ["host", "region", "ghost"][rng.integers(0, 3)]
            op = ops[rng.integers(0, len(ops))]
            if op in ("in", "nin"):
                val = [f"h{rng.integers(0, 45)}" for _ in range(3)]
            elif op in ("re", "nre"):
                val = re.compile(f"[hr]{rng.integers(0, 45)}.*")
            else:
                val = f"h{rng.integers(0, 45)}"
            m.append((tag, op, val))
        np.testing.assert_array_equal(
            ix.match_sids(m), reg.match_sids(m), err_msg=repr(m))


def test_result_cache_hits_and_version_invalidation():
    reg = _make_registry(n=500, hosts=4)
    ix = TagIndex(reg)
    m = [("host", "eq", "h1")]
    a = ix.match_sids(m)
    h0 = ix.stats()["hits"]
    b = ix.match_sids(m)
    assert ix.stats()["hits"] == h0 + 1
    np.testing.assert_array_equal(a, b)
    v0 = reg.version
    # a new series carrying h1 must appear despite the cached result
    reg.intern_rows([np.asarray(["h1"], object),
                     np.asarray(["rz"], object)])
    assert reg.version > v0
    c = ix.match_sids(m)
    assert len(c) == len(a) + 1
    np.testing.assert_array_equal(c, reg.match_sids(m))


def test_delta_tail_avoids_rebuild_then_rebuilds():
    _index.configure({"rebuild_threshold": 64})
    try:
        reg = _make_registry(n=300, hosts=6)
        ix = TagIndex(reg)
        ix.match_sids([("host", "eq", "h1")])
        b0 = ix.stats()["builds"]
        # small delta: evaluated from the tail, no re-sort
        reg.intern_rows([np.asarray(["h1"] * 10, object),
                         np.asarray(["rd"] * 10, object)])
        np.testing.assert_array_equal(
            ix.match_sids([("host", "eq", "h1")]),
            reg.match_sids([("host", "eq", "h1")]))
        assert ix.stats()["builds"] == b0
        # past the threshold: postings rebuild
        many = np.asarray([f"x{i}" for i in range(200)], object)
        reg.intern_rows([many, np.asarray(["rd"] * 200, object)])
        np.testing.assert_array_equal(
            ix.match_sids([("host", "ne", "h1")]),
            reg.match_sids([("host", "ne", "h1")]))
        assert ix.stats()["builds"] == b0 + 1
    finally:
        _index.configure({"rebuild_threshold": 4096})


def test_add_tag_widens_and_rebuilds():
    reg = _make_registry(n=200, hosts=3)
    ix = TagIndex(reg)
    ix.match_sids([("host", "eq", "h0")])
    reg.add_tag("dc")
    reg.intern_rows([np.asarray(["h0"], object),
                     np.asarray(["r0"], object),
                     np.asarray(["east"], object)])
    for m in ([("dc", "eq", "east")], [("dc", "eq", "")],
              [("host", "eq", "h0"), ("dc", "ne", "east")]):
        np.testing.assert_array_equal(
            ix.match_sids(m), reg.match_sids(m), err_msg=repr(m))


def test_disabled_index_falls_back_to_registry():
    reg = _make_registry(n=100)
    m = [("host", "eq", "h1")]
    _index.configure({"enable": False})
    try:
        c = global_registry.counter(
            "gtpu_index_lookups_total", labels=("path",)
        ).labels("host")
        v0 = c.value
        np.testing.assert_array_equal(
            _index.match_sids(reg, m), reg.match_sids(m))
        assert c.value == v0 + 1
    finally:
        _index.configure({"enable": True})


def test_matcher_key_normalizes():
    r = re.compile("h.*")
    assert _index.matcher_key([("host", "re", r)]) == \
        _index.matcher_key([("host", "re", re.compile("h.*"))])
    assert _index.matcher_key([("host", "in", ["b", "a"])]) == \
        _index.matcher_key([("host", "in", ("a", "b"))])


def test_registry_version_bumps():
    reg = SeriesRegistry(["host"])
    v = reg.version
    reg.intern_rows([np.asarray(["a", "b"], object)])
    assert reg.version > v
    v = reg.version
    reg.intern_rows([np.asarray(["a"], object)])  # no new series
    assert reg.version == v
    reg.ensure_series(2, ["c"])
    assert reg.version > v
    v = reg.version
    reg.add_tag("dc")
    assert reg.version > v
    restored = SeriesRegistry.restore(reg.snapshot())
    assert restored.version == len(restored)


def test_compile_matcher_memoized():
    from greptimedb_tpu.query.expr import compile_matcher

    a = compile_matcher("h[0-9]+")
    b = compile_matcher("h[0-9]+")
    assert a is b
    assert a.match("h12")


# -- device plane ------------------------------------------------------

def test_device_plane_mask_parity_and_census():
    reg = _make_registry(n=700, hosts=9)
    s_pad = 1024
    for m in CASES:
        if not m:
            continue
        out = device_plane.matcher_mask_dev(reg, m, s_pad)
        if out is None:  # constant-true-only sets fall back
            continue
        mask, any_match = out
        host = np.zeros(s_pad, bool)
        sids = reg.match_sids(m)
        host[sids] = True
        np.testing.assert_array_equal(np.asarray(mask), host,
                                      err_msg=repr(m))
        assert bool(any_match) == bool(host.any())
    # census invariant: pool-reported bytes == sum of buffer nbytes
    pool = device_plane._PlanePool()
    stats = pool.stats()
    bufs = list(pool.buffers())
    assert stats["bytes"] == sum(int(a.nbytes) for a, _ in bufs)
    assert stats["bytes"] > 0


def test_device_plane_invalidates_on_registry_growth():
    reg = _make_registry(n=100, hosts=3)
    m = [("host", "eq", "h1")]
    out = device_plane.matcher_mask_dev(reg, m, 256)
    assert out is not None
    reg.intern_rows([np.asarray(["h1"], object),
                     np.asarray(["rn"], object)])
    out2 = device_plane.matcher_mask_dev(reg, m, 256)
    assert out2 is not None
    host = np.zeros(256, bool)
    host[reg.match_sids(m)] = True
    np.testing.assert_array_equal(np.asarray(out2[0]), host)


# -- SST sid pruning ---------------------------------------------------

def _pruned_rg() -> float:
    return global_registry.counter(
        "gtpu_index_pruned_row_groups_total").labels().value


def _pruned_bytes(scope: str) -> float:
    return global_registry.counter(
        "gtpu_index_pruned_bytes_total", labels=("scope",)
    ).labels(scope).value


def test_sst_meta_carries_sid_range_and_prunes_row_groups(tmp_path):
    from greptimedb_tpu.storage.memtable import ColumnarRows
    from greptimedb_tpu.storage.object_store import FsObjectStore
    from greptimedb_tpu.storage.sst import read_sst, write_sst

    store = FsObjectStore(str(tmp_path / "store"))
    n = 4000
    rows = ColumnarRows(
        sid=np.arange(n, dtype=np.int32),
        ts=np.arange(n, dtype=np.int64) + 1000,
        seq=np.arange(n, dtype=np.int64),
        op=np.zeros(n, dtype=np.int8),
        fields={"v": np.arange(n, dtype=np.float64)},
    )
    meta = write_sst(store, "t.parquet", "f1", rows, row_group_rows=512)
    assert meta.sid_min == 0 and meta.sid_max == n - 1
    rg0, by0 = _pruned_rg(), _pruned_bytes("row_group")
    out = read_sst(store, meta, sids=np.asarray([5], np.int32))
    assert out is not None and out.sid.tolist() == [5]
    assert _pruned_rg() > rg0           # 7 of 8 groups dropped
    assert _pruned_bytes("row_group") > by0


def test_region_scan_skips_disjoint_ssts(tmp_path):
    import test_compaction as tc

    r = tc.make_region(tmp_path, trigger=100)
    # two flushes; the second one's sids extend past the first's
    tc.write_flush(r, ["a", "b"], [100, 101], [1.0, 2.0])
    tc.write_flush(r, ["c", "d"], [200, 201], [3.0, 4.0])
    metas = r.manifest.state.ssts
    assert len(metas) == 2
    assert metas[1].sid_min > metas[0].sid_max or \
        metas[0].sid_min > metas[1].sid_max
    by0 = _pruned_bytes("sst")
    sids = r.match_sids([("h", "eq", "d")])
    res = r.scan(sids=sids)
    assert res.rows.fields["v"].tolist() == [4.0]
    assert _pruned_bytes("sst") > by0   # whole first SST skipped
    r.close()


def test_index_pool_registered_with_accountant():
    from greptimedb_tpu.telemetry import memory

    reg = _make_registry(n=50)
    _index.index_for(reg).match_sids([("host", "eq", "h1")])
    pools = {p.name for p in memory.global_accountant.snapshot()}
    assert "tag_index" in pools


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
