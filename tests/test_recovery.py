"""Recovery & startup dataplane: region-parallel open, pipelined SST
restore, manifest checkpoint fallback, WAL truncation after the
recovery flush, and the gtpu_recovery_* telemetry."""

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.errors import SstRestoreError
from greptimedb_tpu.storage import recovery as R
from greptimedb_tpu.storage.engine import EngineConfig, TsdbEngine
from greptimedb_tpu.storage.manifest import RegionManifest
from greptimedb_tpu.storage.object_store import (
    CachedObjectStore,
    FsObjectStore,
    MemoryObjectStore,
)
from greptimedb_tpu.storage.page_cache import global_page_cache
from greptimedb_tpu.storage.region import (
    Region,
    RegionMetadata,
    RegionOptions,
)


def _meta(rid, **opts):
    return RegionMetadata(
        region_id=rid, table="t", tag_names=["h"], field_names=["v"],
        ts_name="ts", options=RegionOptions(**opts),
    )


def _write(region, n=4, ts0=0):
    region.write(
        {"h": np.asarray([f"h{i % 3}" for i in range(n)], object)},
        np.arange(ts0, ts0 + n, dtype=np.int64) * 1000,
        {"v": np.arange(n, dtype=np.float64)},
    )


# ----------------------------------------------------------------------
# region-parallel open
# ----------------------------------------------------------------------

def test_batch_open_parallel(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False)
    eng = TsdbEngine(cfg)
    metas = [_meta(i + 1) for i in range(6)]
    for m in metas:
        r = eng.create_region(m)
        _write(r)
        r.flush()
    eng.close()

    eng2 = TsdbEngine(cfg)
    before = R.stage_totals()
    regions = eng2.open_regions(metas, parallelism=4)
    after = R.stage_totals()
    assert len(regions) == 6
    assert sorted(r.meta.region_id for r in regions) == list(range(1, 7))
    for r in regions:
        assert r.scan().num_rows == 4
        # the registry holds the SAME object the batch returned
        assert eng2.region(r.meta.region_id) is r
    # stage telemetry moved
    assert after.get("manifest_load", 0) > before.get("manifest_load", 0)
    assert after.get("total", 0) > before.get("total", 0)
    eng2.close()


def test_racing_opens_coalesce_to_one_region(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False)
    eng = TsdbEngine(cfg)
    meta = _meta(9)
    builds = []
    orig = eng._build_region

    def slow_build(m):
        builds.append(m.region_id)
        time.sleep(0.05)
        return orig(m)

    eng._build_region = slow_build
    out = []
    threads = [
        threading.Thread(target=lambda: out.append(eng.open_region(meta)))
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(out) == 4
    assert all(r is out[0] for r in out), "racing opens built two regions"
    assert builds == [9], "the open ran more than once"
    eng.close()


def test_open_failure_mid_batch_leaves_registry_consistent(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False)
    eng = TsdbEngine(cfg)
    metas = [_meta(i + 1) for i in range(5)]
    for m in metas:
        r = eng.create_region(m)
        _write(r)
        r.flush()
    eng.close()

    eng2 = TsdbEngine(cfg)
    orig = eng2._build_region
    state = {"fail": True}

    def flaky(m):
        if m.region_id == 3 and state["fail"]:
            state["fail"] = False
            raise RuntimeError("injected open failure")
        return orig(m)

    eng2._build_region = flaky
    with pytest.raises(RuntimeError, match="injected open failure"):
        eng2.open_regions(metas, parallelism=3)
    # failed region absent, the others open
    with pytest.raises(Exception):
        eng2.region(3)
    for rid in (1, 2, 4, 5):
        assert eng2.region(rid).scan().num_rows == 4
    # second attempt succeeds and completes the batch
    regions = eng2.open_regions(metas, parallelism=3)
    assert eng2.region(3).scan().num_rows == 4
    assert len(regions) == 5
    eng2.close()


def test_open_error_reraises_to_waiters(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False)
    eng = TsdbEngine(cfg)
    meta = _meta(4)
    started = threading.Event()

    def bad_build(m):
        started.set()
        time.sleep(0.05)
        raise RuntimeError("opener died")

    eng._build_region = bad_build
    errors = []

    def opener():
        try:
            eng.open_region(meta)
        except RuntimeError as e:
            errors.append(str(e))

    t1 = threading.Thread(target=opener)
    t1.start()
    started.wait(2)
    t2 = threading.Thread(target=opener)  # waiter on the same slot
    t2.start()
    t1.join()
    t2.join()
    assert errors == ["opener died", "opener died"]
    # the placeholder is gone: a later open can retry cleanly
    assert eng._opening == {}
    eng.close()


def test_create_region_duplicate_fails_even_against_inflight_open(
        tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False)
    eng = TsdbEngine(cfg)
    meta = _meta(5)
    started = threading.Event()
    release = threading.Event()
    orig = eng._build_region

    def slow_build(m):
        started.set()
        release.wait(5)
        return orig(m)

    eng._build_region = slow_build
    t = threading.Thread(target=lambda: eng.open_region(meta))
    t.start()
    started.wait(2)
    # the open is in flight: create of the same id must fail atomically
    with pytest.raises(AssertionError):
        eng.create_region(_meta(5))
    release.set()
    t.join()
    # and once the region exists, create still fails
    with pytest.raises(AssertionError):
        eng.create_region(_meta(5))
    eng.close()


def test_background_maintenance_lazy_start(tmp_path):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=True,
                       background_interval_s=0.05)
    eng = TsdbEngine(cfg)
    assert eng._bg is None, "maintenance started with no regions"
    eng.create_region(_meta(1))
    assert eng._bg is not None and eng._bg.is_alive()
    eng.close()
    assert not eng._bg.is_alive()


# ----------------------------------------------------------------------
# manifest checkpoints
# ----------------------------------------------------------------------

def test_manifest_checkpoint_interval_trims_edits():
    store = MemoryObjectStore()
    man = RegionManifest(store, "m", checkpoint_distance=4)
    for i in range(6):
        man.commit({"kind": "edit",
                    "set": {"committed_sequence": i + 1}})
    assert store.exists("m/_checkpoint.json")
    live = [m.path for m in store.list("m/")
            if not m.path.endswith("_checkpoint.json")]
    # edits covered by the checkpoint were trimmed to the suffix
    assert len(live) < 6
    man2 = RegionManifest(store, "m")
    assert man2.version == man.version
    assert man2.state.committed_sequence == 6


def test_torn_manifest_checkpoint_falls_back_with_warning(caplog):
    import logging

    store = MemoryObjectStore()
    man = RegionManifest(store, "m", checkpoint_distance=4)
    for i in range(5):
        man.commit({"kind": "edit",
                    "set": {"committed_sequence": i + 1}})
    man.commit({"kind": "edit", "set": {"committed_sequence": 6}})
    assert store.exists("m/_checkpoint.json")
    store.write("m/_checkpoint.json", b"{torn garbage")
    with caplog.at_level(logging.WARNING,
                         logger="greptimedb_tpu.storage.manifest"):
        man2 = RegionManifest(store, "m")
    assert any("torn manifest checkpoint" in r.message
               for r in caplog.records)
    # fallback replays the retained edit suffix — no crash, and the
    # newest retained state is visible
    assert man2.version == man.version
    assert man2.state.committed_sequence == 6


# ----------------------------------------------------------------------
# WAL truncation after the recovery flush (all three backends)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("backend", ["fs", "object", "shared"])
def test_wal_truncated_after_recovery_flush(tmp_path, backend):
    cfg = EngineConfig(data_root=str(tmp_path / "d"),
                       enable_background=False,
                       wal_backend=backend, wal_topics=2)
    metas = [_meta(i + 1) for i in range(2)]
    eng = TsdbEngine(cfg)
    for m in metas:
        r = eng.create_region(m)
        _write(r, n=5)
    for r in eng.regions():
        r.wal.close()
    del eng  # crash: nothing flushed

    eng2 = TsdbEngine(cfg)
    regions = eng2.open_regions(metas)
    replayed = sum(r.recovery_stats["replayed_entries"] for r in regions)
    assert replayed > 0, "crash left no WAL tail to replay"
    for r in regions:
        # the recovery flush persisted the replayed rows
        assert len(r.manifest.state.ssts) >= 1
        assert r.scan().num_rows == 5
        r.wal.close()
    del eng2  # crash again

    eng3 = TsdbEngine(cfg)
    regions3 = eng3.open_regions(metas)
    # the NEXT cold start replays nothing: the flush ran the obsolete
    # path (per-region low-watermark only, on shared topics)
    assert sum(r.recovery_stats["replayed_entries"]
               for r in regions3) == 0
    for r in regions3:
        assert r.scan().num_rows == 5
    eng3.close()


def test_flush_after_replay_disabled_keeps_wal(tmp_path):
    cfg = EngineConfig(
        data_root=str(tmp_path / "d"), enable_background=False,
        recovery=R.RecoveryOptions(flush_after_replay=False),
    )
    meta = _meta(1)
    eng = TsdbEngine(cfg)
    r = eng.create_region(meta)
    _write(r, n=3)
    r.wal.close()
    del eng

    eng2 = TsdbEngine(cfg)
    r2 = eng2.open_region(meta)
    assert r2.recovery_stats["replayed_entries"] > 0
    assert len(r2.manifest.state.ssts) == 0  # no recovery flush
    r2.wal.close()
    del eng2
    eng3 = TsdbEngine(cfg)
    r3 = eng3.open_region(meta)
    # without the recovery flush every restart pays the replay again
    assert r3.recovery_stats["replayed_entries"] > 0
    eng3.close()


# ----------------------------------------------------------------------
# pipelined SST restore
# ----------------------------------------------------------------------

def _mk_flushed_region(tmp_path, store, nsst=3, **opts):
    region = Region(_meta(7, **opts), store, str(tmp_path / "wal"))
    for i in range(nsst):
        _write(region, n=4, ts0=i * 10)
        region.flush()
    return region


def test_restore_warms_page_cache_and_reports_stats(tmp_path):
    store = MemoryObjectStore()
    region = _mk_flushed_region(tmp_path, store, nsst=3)
    global_page_cache.clear()
    stats = R.restore_region_ssts(region, prefetch_depth=2)
    assert stats["files"] == 3
    assert stats["bytes"] == sum(
        m.size_bytes for m in region.manifest.state.ssts
    )
    assert stats["installed_cols"] > 0
    for m in region.manifest.state.ssts:
        assert global_page_cache.get((m.path, 0, "__ts")) is not None
    assert region.recovery_stats["sst_restore_ms"] > 0
    region.close()


def test_restore_torn_object_raises_typed_error(tmp_path):
    store = MemoryObjectStore()
    region = _mk_flushed_region(tmp_path, store, nsst=2)
    victim = region.manifest.state.ssts[1]
    store.write(victim.path, store.read(victim.path)[:-7])
    with pytest.raises(SstRestoreError) as ei:
        R.restore_region_ssts(region, prefetch_depth=4)
    assert victim.path in str(ei.value)
    assert "torn" in str(ei.value)
    region.close()


def test_restore_missing_object_raises_typed_error(tmp_path):
    store = MemoryObjectStore()
    region = _mk_flushed_region(tmp_path, store, nsst=2)
    victim = region.manifest.state.ssts[0]
    store.delete(victim.path)
    with pytest.raises(SstRestoreError, match="missing"):
        R.restore_region_ssts(region, prefetch_depth=0)
    region.close()


class _FlakyStore(MemoryObjectStore):
    """Drops the FIRST ranged get per path (transient remote fault)."""

    def __init__(self):
        super().__init__()
        self.failed = set()
        self.range_calls = 0

    def read_range(self, path, offset, length):
        self.range_calls += 1
        if path not in self.failed:
            self.failed.add(path)
            raise IOError(f"injected drop: {path}")
        return super().read_range(path, offset, length)


def test_restore_retries_dropped_ranged_gets(tmp_path):
    store = _FlakyStore()
    region = _mk_flushed_region(tmp_path, store, nsst=3)
    store.failed.clear()  # arm the fault for every SST
    stats = R.restore_region_ssts(region, prefetch_depth=2)
    assert stats["files"] == 3
    # every file paid exactly one retry
    assert store.range_calls == 6
    region.close()


def test_restore_skips_ttl_expired_ssts(tmp_path):
    store = MemoryObjectStore()
    region = _mk_flushed_region(tmp_path, store, nsst=3, ttl_ms=1000)
    # rows live at ts 0..33s; with now far in the future every SST's
    # whole range is outside retention — nothing is fetched
    stats = R.restore_region_ssts(region, prefetch_depth=2,
                                  now_ms=10**12)
    assert stats["skipped_expired"] == 3
    assert stats["files"] == 0 and stats["bytes"] == 0
    # a horizon before the data restores everything
    stats2 = R.restore_region_ssts(region, prefetch_depth=2, now_ms=500)
    assert stats2["files"] == 3 and stats2["skipped_expired"] == 0
    region.close()


def test_restore_bypasses_cached_store(tmp_path):
    inner = MemoryObjectStore()
    region = _mk_flushed_region(tmp_path, inner, nsst=2)
    ssts = list(region.manifest.state.ssts)
    region.close()
    cached = CachedObjectStore(inner, str(tmp_path / "cache"))
    region2 = Region(_meta(7), cached, str(tmp_path / "wal"))
    stats = R.restore_region_ssts(region2, prefetch_depth=2)
    assert stats["files"] == 2
    # restore reads went beneath the cache: no SST object was admitted
    # (restore is read-once and must not evict hot scan data)
    for m in ssts:
        assert cached._key(m.path) not in cached._lru
    region2.close()


def test_engine_open_with_restore_knobs(tmp_path):
    cfg = EngineConfig(
        data_root=str(tmp_path / "d"), enable_background=False,
        recovery=R.RecoveryOptions(restore_ssts=True,
                                   sst_prefetch_depth=2),
    )
    eng = TsdbEngine(cfg)
    meta = _meta(1)
    r = eng.create_region(meta)
    _write(r)
    r.flush()
    eng.close()

    global_page_cache.clear()
    eng2 = TsdbEngine(cfg)
    before = R.stage_totals()
    r2 = eng2.open_region(meta)
    after = R.stage_totals()
    assert after.get("sst_restore", 0) > before.get("sst_restore", 0)
    assert r2.recovery_stats["sst_restore_ms"] > 0
    sst = r2.manifest.state.ssts[0]
    assert global_page_cache.get((sst.path, 0, "__ts")) is not None
    eng2.close()


# ----------------------------------------------------------------------
# telemetry + config plumbing
# ----------------------------------------------------------------------

def test_recovery_metrics_rendered(tmp_path):
    eng = TsdbEngine(EngineConfig(data_root=str(tmp_path / "d"),
                                  enable_background=False))
    eng.create_region(_meta(1))
    eng.close()
    from greptimedb_tpu.telemetry.metrics import global_registry

    text = global_registry.render()
    assert 'gtpu_recovery_stage_ms_total{stage="manifest_load"}' in text
    assert 'gtpu_recovery_stage_ms_total{stage="wal_replay"}' in text
    assert 'gtpu_recovery_stage_ms_total{stage="total"}' in text
    assert "gtpu_recovery_regions_total" in text


def test_recovery_options_from_section():
    opts = R.recovery_options_from({
        "open_parallelism": 2, "sst_prefetch_depth": 7,
        "checkpoint_interval_edits": 16, "flush_after_replay": False,
        "restore_ssts": True,
    })
    assert opts.open_parallelism == 2
    assert opts.sst_prefetch_depth == 7
    assert opts.checkpoint_interval_edits == 16
    assert opts.flush_after_replay is False
    assert opts.restore_ssts is True
    # defaults survive an empty/partial section
    d = R.recovery_options_from({})
    assert d.open_parallelism == R.DEFAULT_OPEN_PARALLELISM
    assert d.sst_prefetch_depth == R.DEFAULT_SST_PREFETCH_DEPTH
    assert d.checkpoint_interval_edits == R.DEFAULT_CHECKPOINT_INTERVAL


# ----------------------------------------------------------------------
# stress: many regions + fault-injected store (slow tier)
# ----------------------------------------------------------------------

class _DroppyStore(FsObjectStore):
    """Deterministically drops ~1% of ranged gets (retry-path stress)."""

    def __init__(self, root):
        super().__init__(root)
        self._rng = np.random.default_rng(1234)
        self._drop_lock = threading.Lock()
        self.drops = 0

    def read_range(self, path, offset, length):
        with self._drop_lock:
            drop = self._rng.random() < 0.01
        if drop:
            self.drops += 1
            raise IOError(f"injected ranged-get drop: {path}")
        return super().read_range(path, offset, length)


@pytest.mark.slow
def test_recovery_stress_200_regions_with_faults(tmp_path):
    root = str(tmp_path / "d")
    cfg = EngineConfig(data_root=root, enable_background=False)
    n = 200
    metas = [_meta(i + 1) for i in range(n)]
    eng = TsdbEngine(cfg)
    for m in metas:
        r = eng.create_region(m)
        _write(r, n=8)
        r.flush()
        _write(r, n=2, ts0=100)  # WAL tail
    for r in eng.regions():
        r.wal.close()
    del eng  # crash

    store = _DroppyStore(root)
    eng2 = TsdbEngine(cfg, store=store)
    t0 = time.perf_counter()
    regions = eng2.open_regions(metas, restore=True)
    wall = time.perf_counter() - t0
    assert len(regions) == n
    assert store.drops > 0, "fault injection never fired"
    replayed = sum(r.recovery_stats["replayed_entries"] for r in regions)
    assert replayed >= n  # every region had a tail
    for r in regions[::37]:
        assert r.scan().num_rows == 10
    print(f"\n200-region faulted recovery: {wall:.2f}s "
          f"({store.drops} dropped gets retried)")
    eng2.close()
