"""Device program profiler (telemetry/device_programs.py, ISSUE 14):
registry folding across every device_call site, lazy XLA cost/roofline
analysis, 3-surface agreement (information_schema ==
/debug/prof/device?format=json == gtpu_device_program_*) across ADMIN
reset, mesh twins not cross-served, on-demand trace capture, and the
statement-statistics program link."""

import json
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.servers.http import HttpServer
from greptimedb_tpu.telemetry import device_programs as DP
from greptimedb_tpu.telemetry.metrics import global_registry


@pytest.fixture()
def registry():
    """A clean process-wide registry with the default config; restores
    whatever configuration the surrounding suite had."""
    old_cfg = DP.global_programs.config
    DP.global_programs.config = DP.ProfilingConfig()
    DP.global_programs.reset()
    yield DP.global_programs
    DP.global_programs.config = old_cfg
    DP.global_programs.reset()


@pytest.fixture()
def no_sessions():
    """Disable persistent query sessions so every warm poll actually
    DISPATCHES a program (a session hit deliberately does not count as
    a registry call)."""
    from greptimedb_tpu.query import sessions

    old = sessions.global_sessions.enabled
    sessions.global_sessions.enabled = False
    yield
    sessions.global_sessions.enabled = old


@pytest.fixture()
def inst(tmp_path, registry, no_sessions):
    s = Standalone(str(tmp_path / "data"), prefer_device=True,
                   warm_start=False)
    yield s
    s.close()


@pytest.fixture()
def server(inst):
    srv = HttpServer(inst, port=0).start()
    yield srv
    srv.stop()


def _get(srv, path):
    url = f"http://127.0.0.1:{srv.port}{path}"
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.status, r.read().decode()


def _seed(inst, name="cpu", hosts=8, cells=360):
    inst.execute_sql(
        f"create table {name} (ts timestamp time index, "
        "host string primary key, v double)"
    )
    t = inst.catalog.table("public", name)
    rng = np.random.default_rng(3)
    ts = np.tile(np.arange(cells, dtype=np.int64) * 10_000, hosts)
    hs = np.repeat(
        np.asarray([f"h{i}" for i in range(hosts)], object), cells
    )
    t.write({"host": hs}, ts, {"v": rng.random(len(ts))}, skip_wal=True)
    t.flush()
    return t


RANGE_Q = ("SELECT ts, host, avg(v) RANGE '1h' FROM cpu "
           "ALIGN '1h' BY (host)")


def _rows_by_site(registry, *, analyze=False):
    out = {}
    for d in registry.snapshot(analyze=analyze):
        out.setdefault(d["site"], []).append(d)
    return out


# ---------------------------------------------------------------------------
# registry folding across the device call sites
# ---------------------------------------------------------------------------

def test_range_site_folds_one_row_with_calls_accumulating(inst, registry):
    _seed(inst)
    for _ in range(4):
        inst.sql(RANGE_Q)
    assert inst.query_engine.last_exec_path == "device"
    sites = _rows_by_site(registry)
    # ONE row per compiled program, calls accumulating across polls
    assert len(sites["range"]) == 1
    row = sites["range"][0]
    assert row["calls"] == 4
    assert row["compile_ms"] > 0          # first call = compile
    assert row["execute_p50_ms"] > 0      # 3 steady-state samples
    assert row["readback_bytes"] > 0
    # the prelude dispatched once (memoized thereafter)
    assert sites["range_prelude"][0]["calls"] >= 1


def test_groupby_and_merge_and_promql_sites_fold(inst, registry):
    _seed(inst)
    for _ in range(2):
        inst.sql("SELECT host, avg(v), max(v) FROM cpu GROUP BY host")
    sites = _rows_by_site(registry)
    assert len(sites["groupby"]) == 1
    assert sites["groupby"][0]["calls"] == 2

    # device-accelerated compaction merge registers too
    from greptimedb_tpu.storage.device_merge import merge_rows
    from greptimedb_tpu.storage.memtable import ColumnarRows

    n = 4096
    rows = ColumnarRows(
        sid=np.arange(n, dtype=np.int64) % 7,
        ts=np.arange(n, dtype=np.int64),
        seq=np.arange(n, dtype=np.uint64),
        op=np.zeros(n, np.uint8),
        fields={"v": np.arange(n, dtype=np.float64)},
        field_valid=None,
    )
    out, path = merge_rows(rows, device_min_rows=1024)
    assert path == "device" and len(out)
    sites = _rows_by_site(registry)
    assert sites["compact_merge"][0]["calls"] == 1

    # promql fast path (fused query program)
    from greptimedb_tpu.promql import fast as F
    from greptimedb_tpu.promql.engine import PromEngine

    F.invalidate_cache()
    try:
        inst.sql(
            "CREATE TABLE req_total (host STRING, greptime_value "
            "DOUBLE, ts TIMESTAMP TIME INDEX, PRIMARY KEY (host))"
        )
        t = inst.catalog.table("public", "req_total")
        ts = 1_700_000_000_000 + np.arange(41) * 15_000
        for h in range(4):
            t.write({"host": np.full(41, f"h{h}", object)}, ts,
                    {"greptime_value": np.cumsum(np.ones(41))})
        eng = PromEngine(inst)
        for _ in range(2):
            val, _ev = eng.query_range(
                "sum by (host) (rate(req_total[1m]))",
                int(ts[5]), int(ts[-1]), 30_000,
            )
        sites = _rows_by_site(registry)
        assert sites["promql"][0]["calls"] == 2
    finally:
        F.invalidate_cache()


def test_flow_sites_fold(tmp_path, registry, no_sessions):
    """Satellite: the two flow/device_state.py jit programs carry
    registry rows (they were the only device dispatches with zero
    telemetry)."""
    s = Standalone(str(tmp_path / "data"))
    try:
        s.enable_flows(tick_interval_s=3600)
        s.sql(
            "CREATE TABLE src (host STRING, v DOUBLE, ts TIMESTAMP "
            "TIME INDEX, PRIMARY KEY (host))"
        )
        s.sql(
            "CREATE FLOW f1 SINK TO out1 AS SELECT host, count(v), "
            "sum(v), avg(v) FROM src GROUP BY host"
        )
        assert s.flows._flows["f1"].device_state is not None
        t0 = 1_700_000_000_000
        for i in range(2):
            s.sql(
                "INSERT INTO src (host, v, ts) VALUES "
                + ", ".join(f"('h{j}', {j}.5, {t0 + i * 1000})"
                            for j in range(4))
            )
            s.flows.flush_all()
        sites = _rows_by_site(DP.global_programs)
        assert sites["flow_apply"][0]["calls"] >= 2
        assert sites["flow_finalize"][0]["calls"] >= 2
        # apply deliberately does not block: achieved rates suppressed
        assert sites["flow_apply"][0]["dispatch_only"] is True
        assert sites["flow_finalize"][0]["dispatch_only"] is False
        assert sites["flow_finalize"][0]["readback_bytes"] > 0
    finally:
        s.close()


def test_session_hit_does_not_count_a_dispatch(tmp_path, registry):
    """With sessions ON, the warm poll serves the HBM-resident buffer
    without dispatching — the registry counts real dispatches only."""
    s = Standalone(str(tmp_path / "data"), prefer_device=True,
                   warm_start=False)
    try:
        _seed(s)
        for _ in range(3):
            s.sql(RANGE_Q)
        row = _rows_by_site(DP.global_programs)["range"][0]
        assert row["calls"] == 1  # cold dispatch only
    finally:
        s.close()


# ---------------------------------------------------------------------------
# XLA analysis + roofline
# ---------------------------------------------------------------------------

def test_analysis_and_roofline_verdict(inst, registry):
    _seed(inst)
    for _ in range(3):
        inst.sql(RANGE_Q)
    # default CPU config: achieved-only (no peaks -> no verdict)
    docs = registry.snapshot()  # triggers the lazy analysis
    rng_row = [d for d in docs if d["site"] == "range"][0]
    assert rng_row["analysis"] == "ok"
    assert rng_row["flops"] > 0
    assert rng_row["bytes_accessed"] > 0
    assert rng_row["temp_bytes"] >= 0
    assert rng_row["output_bytes"] > 0
    assert rng_row["achieved_gflops"] > 0
    assert rng_row["bound"] == "" and rng_row["pct_of_peak"] == 0.0
    pf, pb, plat, src = registry.peaks()
    assert plat == "cpu" and src == "achieved_only"

    # explicit peaks -> roofline verdict + %-of-peak on every analyzed
    # row with steady-state samples
    registry.config = DP.ProfilingConfig(peak_tflops=0.1,
                                         peak_hbm_gbps=25.0)
    row = [d for d in registry.snapshot() if d["site"] == "range"][0]
    assert row["bound"] in ("compute", "memory")
    assert row["pct_of_peak"] > 0
    # classification is consistent with the operational intensity
    intensity = row["flops"] / row["bytes_accessed"]
    balance = (0.1 * 1e12) / (25.0 * 1e9)
    assert row["bound"] == (
        "compute" if intensity >= balance else "memory"
    )


def test_analysis_disabled_keeps_per_call_stats(inst, registry):
    registry.config = DP.ProfilingConfig(analysis=False)
    _seed(inst)
    inst.sql(RANGE_Q)
    row = _rows_by_site(registry, analyze=True)["range"][0]
    assert row["analysis"] == "off"
    assert row["flops"] == 0.0
    assert row["calls"] == 1 and row["compile_ms"] > 0


def test_lru_collapse_into_other_keeps_totals(registry):
    import jax.numpy as jnp

    from greptimedb_tpu.telemetry import device_trace

    registry.config = DP.ProfilingConfig(max_programs=2, analysis=False)

    def dispatch(i):
        with device_trace.device_call("t", key=("t", i)) as d:
            out = d.run(lambda x: x, jnp.zeros(4))
            d.executed()
            d.transfer(16)
        return out

    for i in range(4):
        dispatch(i)
    docs = registry.snapshot(analyze=False)
    other = [d for d in docs if d["program"] == DP.OTHER]
    assert other and other[0]["site"] == "t"
    total_calls = sum(d["calls"] for d in docs)
    assert total_calls == 4  # collapsed rows' totals never vanish
    assert sum(d["readback_bytes"] for d in docs) == 64
    assert registry.evicted_rows > 0


def test_metric_label_cap_collapses_to_other(registry):
    """Prometheus series can never be evicted, so past the first-come
    metric_programs cap churned programs export under program="_other"
    with counters SUMMED (the registry rows keep their own identity —
    only the exported label collapses)."""
    import jax.numpy as jnp

    from greptimedb_tpu.telemetry import device_trace

    registry.config = DP.ProfilingConfig(metric_programs=2,
                                         analysis=False)
    registry._metric_progs.clear()
    for i in range(4):
        with device_trace.device_call("mc", key=("mc", i)) as d:
            d.run(lambda x: x, jnp.zeros(2))
            d.executed()
            d.transfer(8)
    global_registry.render()
    calls = global_registry.get("gtpu_device_program_calls_total")
    granted = [
        (key, child.value) for key, child in calls._snapshot()
        if key[0] == "mc" and child.value > 0
    ]
    by_prog = dict(granted)
    # 2 granted labels with 1 call each + _other summing the 2 extras
    assert by_prog.get(("mc", DP.OTHER)) == 2.0, granted
    assert sorted(v for (s, p), v in by_prog.items()
                  if p != DP.OTHER) == [1.0, 1.0]
    # the registry rows themselves keep per-program identity
    docs = [d for d in registry.snapshot(analyze=False)
            if d["site"] == "mc"]
    assert len(docs) == 4


# ---------------------------------------------------------------------------
# surfaces: information_schema == /debug/prof/device == metrics,
# across ADMIN reset
# ---------------------------------------------------------------------------

def _surface_triple(inst, server):
    """(information_schema rows, /debug json rows, metric values) keyed
    by (site, program)."""
    info = {}
    r = inst.sql(
        "SELECT site, program, calls, bound, pct_of_peak, flops "
        "FROM information_schema.device_programs"
    )
    for row in r.rows():
        info[(row[0], row[1])] = (row[2], row[3], row[4], row[5])
    status, body = _get(server, "/debug/prof/device?format=json&top=0")
    assert status == 200
    route = {}
    doc = json.loads(body)
    for d in doc["programs"]:
        route[(d["site"], d["program"])] = (
            d["calls"], d["bound"], d["pct_of_peak"], d["flops"]
        )
    global_registry.render()  # refresh the pull-model families
    mets = {}
    calls = global_registry.get("gtpu_device_program_calls_total")
    pct = global_registry.get("gtpu_device_program_pct_of_peak")
    flops = global_registry.get("gtpu_device_program_flops")
    for key, child in calls._snapshot():
        if child.value > 0:
            mets[key] = (int(child.value),
                         pct.labels(*key).value,
                         flops.labels(*key).value)
    return info, route, mets


def test_three_surface_agreement_across_admin_reset(inst, server,
                                                    registry):
    registry.config = DP.ProfilingConfig(peak_tflops=0.1,
                                         peak_hbm_gbps=25.0)
    _seed(inst)
    for _ in range(3):
        inst.sql(RANGE_Q)
    info, route, mets = _surface_triple(inst, server)
    assert info and info == route
    for key, (calls, bound, pct_v, flops_v) in info.items():
        assert mets[key] == (calls, pct_v, flops_v), key
    rng_key = [k for k in info if k[0] == "range"][0]
    assert info[rng_key][1] in ("compute", "memory")
    assert info[rng_key][2] > 0

    # ADMIN reset drops every row; all three surfaces zero together
    r = inst.sql("admin reset_device_profiler()")
    assert r.rows()[0][0] >= 2
    info2, route2, mets2 = _surface_triple(inst, server)
    assert info2 == {} and route2 == {}
    assert mets2 == {}  # published series zeroed, not frozen

    # fresh dispatches after the reset: surfaces agree again
    inst.sql(RANGE_Q)
    info3, route3, mets3 = _surface_triple(inst, server)
    assert info3 and info3 == route3
    for key, (calls, bound, pct_v, flops_v) in info3.items():
        assert mets3[key] == (calls, pct_v, flops_v), key


def test_debug_route_text_face(inst, server, registry):
    _seed(inst)
    inst.sql(RANGE_Q)
    status, text = _get(server, "/debug/prof/device")
    assert status == 200
    assert "device programs:" in text
    assert "range" in text and "compile" in text


def test_debug_route_bad_params(server):
    with pytest.raises(urllib.request.HTTPError):
        _get(server, "/debug/prof/device?top=bogus")
    with pytest.raises(urllib.request.HTTPError):
        _get(server, "/debug/prof/device/trace?seconds=bogus")
    with pytest.raises(urllib.request.HTTPError):
        _get(server, "/debug/prof/device/trace?seconds=0")
    with pytest.raises(urllib.request.HTTPError):
        _get(server, "/debug/prof/device/trace?seconds=120")


# ---------------------------------------------------------------------------
# on-demand trace capture
# ---------------------------------------------------------------------------

def test_trace_capture_writes_loadable_trace(tmp_path, inst, registry):
    _seed(inst)
    inst.sql(RANGE_Q)
    doc = DP.capture_trace(0.2, str(tmp_path / "traces"))
    assert doc["seconds"] == 0.2
    assert doc["trace_dir"].startswith(str(tmp_path / "traces"))
    # jax.profiler wrote a TensorBoard/perfetto-loadable capture
    assert any(f.endswith((".xplane.pb", ".trace.json.gz"))
               for f in doc["files"]), doc["files"]


def test_trace_capture_route(inst, server, registry, tmp_path):
    status, body = _get(
        server,
        "/debug/prof/device/trace?seconds=0.1"
        f"&dir={tmp_path / 'rt'}",
    )
    assert status == 200
    doc = json.loads(body)
    assert doc["files"], doc


def test_trace_capture_busy_is_typed(registry, tmp_path):
    import threading

    DP._capture_active = True
    try:
        with pytest.raises(DP.CaptureBusyError):
            DP.capture_trace(0.1, str(tmp_path))
    finally:
        DP._capture_active = False
    # sanity: flag cleanup (the finally in capture_trace) lets the next
    # capture proceed
    doc = DP.capture_trace(0.05, str(tmp_path))
    assert doc["seconds"] == 0.05
    assert not DP._capture_active
    del threading


# ---------------------------------------------------------------------------
# attribution: stmt_stats link + EXPLAIN ANALYZE roofline attrs
# ---------------------------------------------------------------------------

def test_stmt_stats_rows_link_program_ids(inst, registry):
    from greptimedb_tpu.telemetry.stmt_stats import global_stmt_stats

    _seed(inst)
    global_stmt_stats.reset()
    for _ in range(2):
        inst.sql(RANGE_Q)
    docs = [d for d in global_stmt_stats.snapshot()
            if "range" in d["query"] and d["calls"] >= 2]
    assert docs, "expected a statement row for the range poll"
    prog_ids = {d["program"] for d in registry.snapshot(analyze=False)}
    linked = set(docs[0]["program_ids"])
    assert linked and linked <= prog_ids
    # the SQL face carries the same link (JSON-encoded)
    r = inst.sql(
        "SELECT program_ids FROM information_schema."
        "statement_statistics WHERE calls >= 2"
    )
    all_linked = set()
    for row in r.rows():
        all_linked |= set(json.loads(row[0]))
    assert linked <= all_linked


def test_session_hit_still_attributes_program(tmp_path, registry):
    """With sessions ON the warm poll skips the dispatch, but EXPLAIN
    ANALYZE and traced polls must not lose the program link — the
    registry row is looked up read-only (and NOT folded: no per-call
    achieved-rate claims for a call that ran no program)."""
    from greptimedb_tpu.telemetry import tracing

    registry.config = DP.ProfilingConfig(peak_tflops=0.1,
                                         peak_hbm_gbps=25.0)
    s = Standalone(str(tmp_path / "data"), prefer_device=True,
                   warm_start=False)
    try:
        _seed(s)
        s.sql(RANGE_Q)  # cold: the one real dispatch
        registry.analyze_pending()
        row = [d for d in registry.snapshot(analyze=False)
               if d["site"] == "range"][0]
        assert row["calls"] == 1
        r = s.sql("EXPLAIN ANALYZE " + RANGE_Q)  # warm: session hit
        text = "\n".join(str(t[-1]) for t in r.rows())
        assert "device_session: hit" in text
        assert f"device_program_range: {row['program']}" in text
        assert "served from the session buffer" in text
        with tracing.span("req") as root:
            s.sql(RANGE_Q)
        dev = [sp for sp in tracing.global_traces.trace(root.trace_id)
               if sp["name"] == "device.execute"
               and sp["attributes"].get("site") == "range"]
        attrs = dev[0]["attributes"]
        assert attrs["program"] == row["program"]
        assert attrs["roofline_bound"] in ("compute", "memory")
        # no dispatch happened: no per-call achieved claims, no fold
        assert "achieved_gflops" not in attrs
        row2 = [d for d in registry.snapshot(analyze=False)
                if d["site"] == "range"][0]
        assert row2["calls"] == 1
    finally:
        s.close()


def test_explain_analyze_carries_program_and_roofline(inst, registry):
    registry.config = DP.ProfilingConfig(peak_tflops=0.1,
                                         peak_hbm_gbps=25.0)
    _seed(inst)
    for _ in range(2):
        inst.sql(RANGE_Q)
    registry.analyze_pending()  # surfaces consulted -> analysis done
    r = inst.sql("EXPLAIN ANALYZE " + RANGE_Q)
    text = "\n".join(str(row[-1]) for row in r.rows())
    assert "device_program_range" in text
    assert "roofline_range" in text
    assert "-bound" in text and "% of peak" in text


# ---------------------------------------------------------------------------
# mesh twins are not cross-served
# ---------------------------------------------------------------------------

def test_mesh_twins_get_distinct_rows(tmp_path, rng, devices, registry,
                                      no_sessions):
    from greptimedb_tpu.parallel import mesh as M
    from greptimedb_tpu.query.executor import QueryEngine
    from greptimedb_tpu.query.planner import plan_select
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.sql.parser import parse_sql

    del plan_select
    inst = Standalone(str(tmp_path))
    try:
        inst.execute_sql(
            "create table cpu (ts timestamp time index, host string "
            "primary key, u double)"
        )
        tab = inst.catalog.table("public", "cpu")
        n_hosts, t = 16, 120
        ts = np.tile(np.arange(t) * 10_000, n_hosts).astype(np.int64)
        hosts = np.repeat(
            [f"h{i:02d}" for i in range(n_hosts)], t
        ).astype(object)
        tab.write({"host": hosts}, ts, {"u": rng.random(n_hosts * t)})
        q = "SELECT host, sum(u), avg(u) FROM cpu GROUP BY host"
        em = QueryEngine(prefer_device=True, mesh=M.make_mesh(devices),
                         mesh_opts=M.MeshOptions(shard_min_series=1,
                                                 shard_min_rows=1))
        es = QueryEngine(prefer_device=True)

        def run(engine):
            stmt = parse_sql(q)[0]
            plan, table = inst.plan(stmt, QueryContext())
            return engine.execute(plan, table)

        run(es)
        run(es)
        run(em)
        assert em.last_exec_path == "device"
        rows = _rows_by_site(DP.global_programs)["groupby"]
        # the single-device program and the shard_map twin fold into
        # DISTINCT registry rows — never cross-served
        assert len(rows) == 2
        by_calls = sorted(r["calls"] for r in rows)
        assert by_calls == [1, 2]
        assert rows[0]["program"] != rows[1]["program"]
    finally:
        inst.close()
