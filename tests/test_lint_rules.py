"""gtlint rule fixtures: every rule has at least one positive snippet
(caught, with the right rule id and line) and one negative snippet
(not flagged), plus suppression-comment and baseline round-trips and
the CLI/JSON surface."""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

import pytest

from greptimedb_tpu.tools.lint import (
    Baseline,
    all_rules,
    lint_paths,
    lint_source,
)


def run_lint(src: str, select: str | None = None):
    act, sup = lint_source(
        "fixture.py", textwrap.dedent(src),
        select={select} if select else None,
    )
    return act, sup


def rules_hit(src: str, select: str | None = None):
    act, _ = run_lint(src, select)
    return [(f.rule, f.line) for f in act]


def test_registry_has_all_rules():
    ids = sorted(all_rules())
    # GT020 is unassigned/reserved; the registry jumps to GT021.
    assert ids == ([f"GT{n:03d}" for n in range(1, 20)]
                   + [f"GT{n:03d}" for n in range(21, 34)])
    for rule in all_rules().values():
        assert rule.name and rule.description


# ---------------------------------------------------------------------------
# GT001 silent exception swallow
# ---------------------------------------------------------------------------

def test_gt001_positive_swallow_and_bare():
    hits = rules_hit("""
        try:
            x = 1
        except Exception:
            pass
    """)
    assert ("GT001", 4) in hits

    hits = rules_hit("""
        try:
            x = 1
        except:
            x = 2
    """)
    assert ("GT001", 4) in hits


def test_gt001_negative_narrow_or_logged():
    assert rules_hit("""
        try:
            x = 1
        except ValueError:
            pass
    """) == []
    assert rules_hit("""
        import logging
        try:
            x = 1
        except Exception as e:
            logging.getLogger("x").warning("boom: %s", e)
    """) == []


# ---------------------------------------------------------------------------
# GT002 error-substring matching
# ---------------------------------------------------------------------------

def test_gt002_positive_str_e_matching():
    hits = rules_hit("""
        def classify(e):
            return "unavailable" in str(e).lower()
    """)
    assert ("GT002", 3) in hits
    hits = rules_hit("""
        try:
            x = 1
        except Exception as boom:
            if "not found" in str(boom):
                raise
    """)
    assert ("GT002", 5) in hits


def test_gt002_negative_plain_string_ops():
    # substring tests on non-exception values are fine
    assert rules_hit("""
        def f(value):
            return "," in str(value)
    """) == []
    assert rules_hit("""
        def f(e):
            return isinstance(e, ConnectionError)
    """) == []


# ---------------------------------------------------------------------------
# GT003 untyped raise
# ---------------------------------------------------------------------------

def test_gt003_positive_untyped():
    assert ("GT003", 2) in rules_hit("""
        raise Exception("boom")
    """)
    assert ("GT003", 2) in rules_hit("""
        raise BaseException("boom")
    """)


def test_gt003_negative_typed():
    assert rules_hit("""
        from greptimedb_tpu.errors import StorageError
        def f():
            raise StorageError("disk gone")
        def g():
            raise ValueError("bad arg")
    """) == []


# ---------------------------------------------------------------------------
# GT004 host sync inside jit / Pallas
# ---------------------------------------------------------------------------

def test_gt004_positive_item_float_asarray():
    hits = rules_hit("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = x.item()
            b = float(x)
            c = np.asarray(x)
            return a + b + c.sum()
    """)
    assert [h[0] for h in hits] == ["GT004", "GT004", "GT004"]
    assert [h[1] for h in hits] == [7, 8, 9]


def test_gt004_positive_inside_pallas_kernel():
    hits = rules_hit("""
        from jax.experimental import pallas as pl

        def my_kernel(x_ref, o_ref):
            o_ref[0] = float(x_ref)

        def launch(x):
            return pl.pallas_call(my_kernel, out_shape=None)(x)
    """)
    assert ("GT004", 5) in hits


def test_gt004_positive_inside_shard_map_body():
    # shard_map bodies run traced on device exactly like jit/Pallas
    hits = rules_hit("""
        import jax
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def local(x):
                return np.asarray(x).sum()

            return shard_map(local, mesh=mesh, in_specs=(P("s"),),
                             out_specs=P())(x)
    """)
    assert ("GT004", 9) in hits


def test_gt005_positive_inside_shard_map_body():
    hits = rules_hit("""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def local(x):
                if x > 0:
                    x = x - 1
                return x

            return shard_map(local, mesh=mesh, in_specs=(P("s"),),
                             out_specs=P("s"))(x)
    """)
    assert ("GT005", 7) in hits


def test_gt004_negative_host_code_and_static():
    # outside jit, all of these are normal host code
    assert rules_hit("""
        import numpy as np
        def f(x):
            return float(x) + np.asarray(x).sum() + x.item()
    """) == []
    # float() of a static (non-traced) value inside jit is fine
    assert rules_hit("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def f(x, k):
            return x * float(k)
    """) == []


# ---------------------------------------------------------------------------
# GT005 Python branch on traced value
# ---------------------------------------------------------------------------

def test_gt005_positive_if_while_ifexp():
    hits = rules_hit("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                x = x - 1
            while x < 3:
                x = x + 1
            return x if x > 0 else -x
    """)
    assert [h[0] for h in hits] == ["GT005", "GT005", "GT005"]
    assert [h[1] for h in hits] == [6, 8, 10]


def test_gt005_negative_static_shape_none_isinstance():
    assert rules_hit("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def f(x, k, opt=None):
            if k > 1:
                x = x * 2
            if x.ndim == 2:
                x = x.sum(axis=1)
            if opt is None:
                x = x + 1
            if len(x.shape) == 1:
                x = x * 3
            return x
    """) == []


# ---------------------------------------------------------------------------
# GT006 recompile hazards
# ---------------------------------------------------------------------------

def test_gt006_positive_jit_in_loop_and_lambda():
    hits = rules_hit("""
        import jax

        def g(h, xs):
            for x in xs:
                f = jax.jit(h)
            f2 = jax.jit(lambda a: a + 1)
            return f, f2
    """)
    assert [h[0] for h in hits] == ["GT006", "GT006"]
    assert [h[1] for h in hits] == [6, 7]


def test_gt006_negative_module_scope_jit():
    assert rules_hit("""
        import functools
        import jax

        def _impl(x):
            return x + 1

        fast = jax.jit(_impl)
        faster = functools.partial(jax.jit, static_argnames=("k",))
    """) == []


# ---------------------------------------------------------------------------
# GT007 lock across blocking I/O
# ---------------------------------------------------------------------------

def test_gt007_positive_urlopen_flight_sleep_under_lock():
    hits = rules_hit("""
        import threading
        import time
        import urllib.request

        lock = threading.Lock()

        def f(client):
            with lock:
                urllib.request.urlopen("http://x")
            with client._lock:
                client.conn.do_get(b"t")
            with lock:
                time.sleep(1.0)
    """)
    # the same unbounded urlopen/do_get also trip GT012: filter to the
    # lock-discipline findings this test is about
    gt007 = [h for h in hits if h[0] == "GT007"]
    assert [h[1] for h in gt007] == [10, 12, 14]
    assert {h[0] for h in hits} == {"GT007", "GT012"}


def test_gt007_negative_io_outside_lock_and_condvar():
    assert rules_hit("""
        import threading
        import urllib.request

        lock = threading.Lock()
        cond = threading.Condition()

        def f():
            with lock:
                snapshot = 1
            urllib.request.urlopen("http://x", timeout=5.0)
            with cond:
                cond.wait()   # releases the lock: allowed
            return snapshot
    """) == []


# ---------------------------------------------------------------------------
# GT008 thread/pool without join/shutdown
# ---------------------------------------------------------------------------

def test_gt008_positive_leaked_thread_and_pool():
    hits = rules_hit("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def bad(target):
            threading.Thread(target=target).start()
            pool = ThreadPoolExecutor(4)
            return pool
    """)
    assert [h[0] for h in hits] == ["GT008", "GT008"]
    assert [h[1] for h in hits] == [6, 7]


def test_gt008_negative_daemon_join_with_shutdown():
    assert rules_hit("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def ok(target):
            threading.Thread(target=target, daemon=True).start()
            t = threading.Thread(target=target)
            t.start()
            t.join()
            with ThreadPoolExecutor(4) as p:
                p.submit(target)
            q = ThreadPoolExecutor(2)
            q.shutdown(wait=False)
    """) == []


def test_gt008_negative_swap_teardown_idiom():
    # the codebase's shutdown-outside-the-lock idiom must not flag
    assert rules_hit("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Server:
            def _pool(self):
                with self._lock:
                    if self._scan_pool is None:
                        self._scan_pool = ThreadPoolExecutor(4)
                    return self._scan_pool

            def close(self):
                with self._lock:
                    pool, self._scan_pool = self._scan_pool, None
                if pool is not None:
                    pool.shutdown(wait=False)
    """) == []


# ---------------------------------------------------------------------------
# GT009 int64 on device
# ---------------------------------------------------------------------------

def test_gt009_positive_jnp_int64():
    hits = rules_hit("""
        import jax.numpy as jnp
        import numpy as np

        def f(x):
            a = jnp.asarray(x, jnp.int64)
            b = jnp.zeros(3, dtype=np.int64)
            c = jnp.zeros(3, dtype="int64")
            return a, b, c
    """)
    assert [h[0] for h in hits] == ["GT009", "GT009", "GT009"]
    assert [h[1] for h in hits] == [6, 7, 8]


def test_gt009_negative_host_numpy_and_int32():
    assert rules_hit("""
        import jax.numpy as jnp
        import numpy as np

        def f(x):
            host = np.asarray(x, np.int64)      # host numpy: fine
            dev = jnp.asarray(x, jnp.int32)
            return host, dev
    """) == []


# ---------------------------------------------------------------------------
# GT010 mutable default args
# ---------------------------------------------------------------------------

def test_gt010_positive_public_mutable_defaults():
    hits = rules_hit("""
        def public(a, xs=[], m={}, s=set()):
            return a
    """)
    assert [h[0] for h in hits] == ["GT010", "GT010", "GT010"]


def test_gt010_negative_private_none_tuple():
    assert rules_hit("""
        def _private(xs=[]):
            return xs

        def public(a, xs=None, t=(), name="x"):
            return a
    """) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# GT013 collective axis not bound by the enclosing shard_map
# ---------------------------------------------------------------------------

def test_gt013_positive_unbound_literal_axis():
    hits = rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def local(x):
                return jax.lax.psum(x, "time")

            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P())(x)
    """, select="GT013")
    assert hits == [("GT013", 8)]


def test_gt013_positive_unresolved_identifier_axis():
    # both sides unresolved identifiers: compared by name
    hits = rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from somewhere import AXIS_A, AXIS_B

        def run(mesh, x):
            def local(x):
                return jax.lax.pmax(x, AXIS_B)

            return shard_map(local, mesh=mesh, in_specs=(P(AXIS_A),),
                             out_specs=P())(x)
    """, select="GT013")
    assert hits == [("GT013", 9)]


def test_gt013_positive_module_constant_resolution():
    # module constants resolve to their string values before comparing
    hits = rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        AXIS_S = "shard"

        def run(mesh, x):
            def local(x):
                return jax.lax.all_gather(x, "ici")

            return shard_map(local, mesh=mesh, in_specs=(P(AXIS_S),),
                             out_specs=P())(x)
    """, select="GT013")
    assert hits == [("GT013", 10)]


def test_gt013_negative_bound_axis_and_mixed_spaces():
    # bound literal axis: clean
    assert rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def local(x):
                return jax.lax.psum(x, "shard")

            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P())(x)
    """, select="GT013") == []
    # module constant on both sides: resolves and matches
    assert rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        AXIS_S = "shard"

        def run(mesh, x):
            def local(x):
                return jax.lax.pmin(x, AXIS_S)

            return shard_map(local, mesh=mesh, in_specs=(P(AXIS_S),),
                             out_specs=P())(x)
    """, select="GT013") == []
    # unresolved identifier vs literal specs: can't compare, stays quiet
    assert rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from somewhere import AXIS_T

        def run(mesh, x):
            def local(x):
                return jax.lax.psum(x, AXIS_T)

            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P())(x)
    """, select="GT013") == []
    # collective outside any shard_map body: out of scope
    assert rules_hit("""
        import jax

        def helper(x, axis_name="shard"):
            return jax.lax.psum(x, axis_name)
    """, select="GT013") == []


# ---------------------------------------------------------------------------
# GT014 tracing/metrics calls inside jit/shard_map device scope
# ---------------------------------------------------------------------------

def test_gt014_positive_tracing_span_in_jit():
    hits = rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import tracing

        @jax.jit
        def kernel(x):
            with tracing.span("device.step"):
                return x + 1
    """, select="GT014")
    assert hits == [("GT014", 7)]


def test_gt014_positive_stats_and_metric_in_shard_map_body():
    hits = rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from greptimedb_tpu.query import stats
        from greptimedb_tpu.telemetry.metrics import global_registry

        _CALLS = global_registry.counter("calls", "c", ("k",))

        def run(mesh, x):
            def local(x):
                stats.add("device_steps", 1)
                _CALLS.labels("a").inc()
                return jax.lax.psum(x, "shard")

            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P())(x)
    """, select="GT014")
    assert hits == [("GT014", 12), ("GT014", 13)]


def test_gt014_positive_nested_def_inherits_device_scope():
    # a helper nested inside a jitted function traces on device too
    hits = rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import tracing

        @jax.jit
        def kernel(x):
            def inner(y):
                tracing.event_span("step", 1.0)
                return y

            return inner(x)
    """, select="GT014")
    assert hits == [("GT014", 8)]


def test_gt014_negative_host_scope_and_lowercase_receiver():
    # the same calls OUTSIDE device scope are the intended idiom
    assert rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import tracing
        from greptimedb_tpu.query import stats

        @jax.jit
        def kernel(x):
            return x + 1

        def host(x):
            with tracing.span("device.execute"):
                out = kernel(x)
            stats.add("device_readback_bytes", 8)
            return out
    """, select="GT014") == []
    # lowercase method receivers inside jit are not metric constants
    assert rules_hit("""
        import jax

        @jax.jit
        def kernel(x, acc):
            y = acc.set(1)
            return x.inc() + y.observe()
    """, select="GT014") == []


# ---------------------------------------------------------------------------
# GT015 full-buffer readback on a device result buffer
# ---------------------------------------------------------------------------

def test_gt015_positive_asarray_and_device_get():
    hits = rules_hit("""
        import numpy as np

        def run(program, arrs):
            out = program(arrs)
            out.block_until_ready()
            host = np.asarray(out)
            return host
    """, select="GT015")
    assert hits == [("GT015", 7)]
    hits = rules_hit("""
        import jax

        def run(program, arrs):
            packed = program(arrs)
            packed.block_until_ready()
            return jax.device_get(packed)
    """, select="GT015")
    assert hits == [("GT015", 7)]


def test_gt015_negative_helper_and_host_arrays():
    # readback through the blessed helpers is the intended idiom
    assert rules_hit("""
        from greptimedb_tpu.query import readback

        def run(program, arrs, j0):
            out = program(arrs)
            out.block_until_ready()
            return readback.read_delta(out, j0, axis=-1)
    """, select="GT015") == []
    # np.asarray on a plain host value (no block_until_ready) is fine
    assert rules_hit("""
        import numpy as np

        def convert(vals):
            arr = np.asarray(vals)
            return arr
    """, select="GT015") == []
    # a DIFFERENT function's device buffer does not taint this one
    assert rules_hit("""
        import numpy as np

        def a(program, arrs):
            out = program(arrs)
            out.block_until_ready()
            return out

        def b(out):
            return np.asarray(out)
    """, select="GT015") == []


# ---------------------------------------------------------------------------
# GT016 byte-budgeted container not registered with the memory accountant
# ---------------------------------------------------------------------------

def test_gt016_positive_unregistered_byte_pool():
    hits = rules_hit("""
        from collections import OrderedDict

        class GridCache:
            def __init__(self, max_bytes):
                self.max_bytes = int(max_bytes)
                self._entries = OrderedDict()
                self._bytes = 0
    """, select="GT016")
    assert hits == [("GT016", 4)]
    # budget riding the VALUE name (self.capacity = capacity_bytes)
    hits = rules_hit("""
        class PageCache:
            def __init__(self, capacity_bytes):
                self.capacity = capacity_bytes
                self._entries = {}
    """, select="GT016")
    assert hits == [("GT016", 2)]


def test_gt016_positive_module_dict_of_device_arrays():
    hits = rules_hit("""
        import jax

        _GRIDS = {}

        def cache_grid(key, host_arr):
            _GRIDS[key] = jax.device_put(host_arr)
    """, select="GT016")
    assert [h[0] for h in hits] == ["GT016"]


def test_gt016_negative_registered_and_non_pools():
    # registering with the accountant silences the rule
    assert rules_hit("""
        from collections import OrderedDict
        from greptimedb_tpu.telemetry import memory

        class GridCache:
            def __init__(self, max_bytes):
                self.max_bytes = int(max_bytes)
                self._entries = OrderedDict()
                memory.register_pool(
                    "grids", "device", self, stats=GridCache._stats
                )

            def _stats(self):
                return {"bytes": 0}
    """, select="GT016") == []
    # entry-count config objects are not byte pools
    assert rules_hit("""
        class TracingConfig:
            def __init__(self, capacity):
                self.capacity = int(capacity)
                self.extra = {}
    """, select="GT016") == []
    # a budget without an entries container (a sizing constant holder)
    assert rules_hit("""
        class Sizer:
            def __init__(self, max_bytes):
                self.max_bytes = max_bytes
    """, select="GT016") == []
    # module dicts holding host-side objects are fine
    assert rules_hit("""
        _LOCKS = {}

        def lock_for(key):
            import threading
            _LOCKS[key] = threading.Lock()
            return _LOCKS[key]
    """, select="GT016") == []
    # a registering module's device-array dict is fine too
    assert rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import memory

        _GRIDS = {}
        memory.register_pool("grids", "device", object(), stats=len)

        def cache_grid(key, host_arr):
            _GRIDS[key] = jax.device_put(host_arr)
    """, select="GT016") == []


def test_suppression_same_line():
    src = """
        try:
            x = 1
        except Exception:  # gtlint: disable=GT001
            pass
    """
    act, sup = run_lint(src)
    assert act == []
    assert [(f.rule, f.line) for f in sup] == [("GT001", 4)]


def test_suppression_next_line_and_multi_id():
    act, sup = run_lint("""
        import jax

        @jax.jit
        def f(x):
            # gtlint: disable-next-line=GT004,GT005
            if x > 0:
                return x
            return float(x)   # gtlint: disable=GT004
    """)
    assert act == []
    assert sorted(f.rule for f in sup) == ["GT004", "GT005"]


def test_suppression_wrong_id_does_not_cover():
    act, _ = run_lint("""
        try:
            x = 1
        except Exception:
            pass  # gtlint: disable=GT999
    """)
    assert [(f.rule, f.line) for f in act] == [("GT001", 4)]


def test_suppression_file_wide():
    act, sup = run_lint("""
        # gtlint: disable-file=GT010
        def public(xs=[]):
            return xs

        def other(m={}):
            return m
    """)
    assert act == []
    assert len(sup) == 2


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

BASELINE_SRC = '''
try:
    x = 1
except Exception:
    pass

def classify(e):
    return "boom" in str(e)
'''


def test_baseline_round_trip(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(BASELINE_SRC)

    # 1) no baseline: both findings are new
    res = lint_paths([str(pkg)], baseline=None)
    res.pop("_line_text", None)
    assert res["counts"]["new"] == 2
    assert not res["clean"]

    # 2) write those findings as the baseline; re-run: clean
    proc = subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.tools.lint", str(pkg),
         "--baseline", str(tmp_path / "base.json"), "--write-baseline"],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    base = Baseline.load(str(tmp_path / "base.json"))
    assert len(base.entries) == 2

    res = lint_paths([str(pkg)], baseline=base)
    res.pop("_line_text", None)
    assert res["counts"]["new"] == 0
    assert res["counts"]["baselined"] == 2
    assert res["clean"]

    # 3) fix one violation: its baseline entry goes stale (reported,
    # and the gate fails until the entry is removed)
    (pkg / "mod.py").write_text(BASELINE_SRC.replace(
        'return "boom" in str(e)', "return isinstance(e, OSError)"
    ))
    res = lint_paths([str(pkg)], baseline=base)
    res.pop("_line_text", None)
    assert res["counts"]["new"] == 0
    assert res["counts"]["baselined"] == 1
    assert res["counts"]["stale_baseline"] == 1
    assert not res["clean"]

    # 4) a NEW violation is never hidden by the baseline
    (pkg / "mod.py").write_text(
        BASELINE_SRC + "\n\ndef pub(xs=[]):\n    return xs\n"
    )
    res = lint_paths([str(pkg)], baseline=base)
    res.pop("_line_text", None)
    assert res["counts"]["new"] == 1
    assert res["findings"][0]["rule"] == "GT010"


def test_baseline_line_drift_tolerated(tmp_path):
    """Edits above a grandfathered site must not invalidate its
    baseline entry: matching is by text, not line number."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(BASELINE_SRC)
    res = lint_paths([str(pkg)], baseline=None)
    line_text = res.pop("_line_text")
    from greptimedb_tpu.tools.lint import Finding

    base = Baseline.from_findings(
        [Finding(**d) for d in res["findings"]], line_text
    )
    (pkg / "mod.py").write_text("import os\nimport sys\n" + BASELINE_SRC)
    res = lint_paths([str(pkg)], baseline=base)
    res.pop("_line_text", None)
    assert res["counts"]["new"] == 0
    assert res["counts"]["stale_baseline"] == 0
    assert res["clean"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def _run_cli(args, cwd="/root/repo"):
    return subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.tools.lint", *args],
        capture_output=True, text=True, cwd=cwd,
    )


def test_cli_json_format_and_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def pub(xs=[]):\n    return xs\n")
    proc = _run_cli([str(bad), "--format=json", "--no-baseline"])
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["counts"]["new"] == 1
    assert doc["findings"][0]["rule"] == "GT010"
    assert doc["findings"][0]["line"] == 1
    assert not doc["clean"]

    good = tmp_path / "good.py"
    good.write_text("def pub(xs=None):\n    return xs\n")
    proc = _run_cli([str(good), "--format=json", "--no-baseline"])
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["clean"]


def test_cli_select_and_list_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def pub(xs=[]):\n"
        "    try:\n"
        "        return xs\n"
        "    except Exception:\n"
        "        pass\n"
    )
    proc = _run_cli([str(bad), "--select=GT001", "--format=json",
                     "--no-baseline"])
    doc = json.loads(proc.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["GT001"]

    proc = _run_cli(["--list-rules"])
    assert proc.returncode == 0
    for rid in ("GT001", "GT005", "GT010"):
        assert rid in proc.stdout


def test_cli_syntax_error_exit_2(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def (\n")
    proc = _run_cli([str(bad), "--no-baseline"])
    assert proc.returncode == 2
    assert "error" in proc.stdout


def test_cli_nonexistent_path_exit_2(tmp_path):
    """A typo'd path must not lint 0 files and report clean."""
    proc = _run_cli([str(tmp_path / "no_such_dir"), "--no-baseline"])
    assert proc.returncode == 2
    assert "does not exist" in proc.stdout


def test_write_baseline_merges_out_of_scope_and_refuses_select(tmp_path):
    """A subdirectory --write-baseline keeps grandfathered entries for
    files outside the run's scope; --select is refused outright."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir()
    b.mkdir()
    (a / "mod.py").write_text("def pub(xs=[]):\n    return xs\n")
    (b / "mod.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    base = tmp_path / "base.json"
    proc = _run_cli([str(a), str(b), "--baseline", str(base),
                     "--write-baseline"])
    assert proc.returncode == 0, proc.stderr
    assert len(Baseline.load(str(base)).entries) == 2

    # re-write scoped to only a/: b/'s entry must survive the merge
    proc = _run_cli([str(a), "--baseline", str(base),
                     "--write-baseline"])
    assert proc.returncode == 0, proc.stderr
    entries = Baseline.load(str(base)).entries
    assert sorted(e["rule"] for e in entries) == ["GT001", "GT010"]

    proc = _run_cli([str(a), "--baseline", str(base),
                     "--write-baseline", "--select=GT010"])
    assert proc.returncode == 2
    assert "--select" in proc.stderr


def test_greptimedb_tpu_cli_lint_subcommand(tmp_path):
    """`greptimedb-tpu lint` (cli.py) mirrors the module CLI."""
    bad = tmp_path / "bad.py"
    bad.write_text("def pub(xs=[]):\n    return xs\n")
    proc = subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.cli", "lint", str(bad),
         "--format=json", "--no-baseline"],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["findings"][0]["rule"] == "GT010"


# ---------------------------------------------------------------------------
# planted multi-violation fixture: ids, files, and lines all correct
# ---------------------------------------------------------------------------

def test_planted_violations_report_correct_rule_file_line(tmp_path):
    pkg = tmp_path / "planted"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "try:\n"
        "    x = 1\n"
        "except Exception:\n"
        "    pass\n"
    )
    (pkg / "b.py").write_text(
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return float(x)\n"
        "    return x\n"
    )
    res = lint_paths([str(pkg)], baseline=None)
    res.pop("_line_text", None)
    got = {(f["rule"], f["path"].rsplit("/", 1)[-1], f["line"])
           for f in res["findings"]}
    assert got == {
        ("GT001", "a.py", 3),
        ("GT005", "b.py", 5),
        ("GT004", "b.py", 6),
    }


def test_lint_source_on_every_rule_doc():
    """Rule descriptions render in --list-rules; ids are stable."""
    rules = all_rules()
    assert rules["GT001"].name == "silent-exception-swallow"
    assert rules["GT007"].name == "lock-across-blocking-io"
    assert rules["GT009"].name == "int64-on-device"
    assert rules["GT011"].name == "wallclock-duration"


# ---------------------------------------------------------------------------
# GT007 interprocedural: blocking taint through module-local helpers
# ---------------------------------------------------------------------------

def test_gt007_interproc_two_calls_deep():
    """lock -> helper -> helper -> do_put fires, with the chain."""
    act, _ = run_lint("""
        import threading

        lock = threading.Lock()

        class Sender:
            def _wire(self, batch):
                writer, reader = self.client.do_put(batch)

            def _send(self, batch):
                return self._wire(batch)

            def submit(self, batch):
                with lock:
                    self._send(batch)
    """)
    hits = [(f.rule, f.line) for f in act]
    assert ("GT007", 15) in hits, hits
    msg = [f.message for f in act if f.line == 15][0]
    assert "Sender._send" in msg and "do_put" in msg


def test_gt007_interproc_module_function_one_deep():
    act, _ = run_lint("""
        import threading
        import time

        lock = threading.Lock()

        def backoff():
            time.sleep(0.5)

        def retry():
            with lock:
                backoff()
    """)
    hits = [(f.rule, f.line) for f in act]
    assert ("GT007", 12) in hits, hits


def test_gt007_interproc_negative_clean_helper_and_async_def():
    # a helper with no blocking op, and a nested def handed to a
    # thread (runs asynchronously), must not taint the caller
    assert rules_hit("""
        import threading
        import time

        lock = threading.Lock()

        def compute():
            return 2 + 2

        def submit():
            def worker():
                time.sleep(5)
            t = threading.Thread(target=worker, daemon=True)
            with lock:
                compute()
            t.start()
    """) == []


def test_gt007_interproc_negative_helper_called_outside_lock():
    assert rules_hit("""
        import threading
        import time

        lock = threading.Lock()

        def backoff():
            time.sleep(0.5)

        def retry():
            with lock:
                x = 1
            backoff()
    """) == []


# ---------------------------------------------------------------------------
# GT004 interprocedural: host-sync taint through helpers in jit
# ---------------------------------------------------------------------------

def test_gt004_interproc_helper_item_on_traced_arg():
    act, _ = run_lint("""
        import jax

        def total(v):
            return v.sum().item()

        @jax.jit
        def kernel(x):
            return total(x)
    """)
    hits = [(f.rule, f.line) for f in act]
    assert ("GT004", 9) in hits, hits
    msg = [f.message for f in act if f.line == 9][0]
    assert "total" in msg and ".item()" in msg


def test_gt004_interproc_negative_static_arg_and_host_caller():
    # helper called on a NON-traced value, and the same helper called
    # from plain host code, both stay clean
    assert rules_hit("""
        import functools

        import jax

        def total(v):
            return v.sum().item()

        @functools.partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            return x * total(n)

        def host(y):
            return total(y)
    """) == []


# ---------------------------------------------------------------------------
# GT011 wall-clock duration arithmetic
# ---------------------------------------------------------------------------

def test_gt011_positive_inline_and_named():
    hits = rules_hit("""
        import time

        def f(start):
            return time.time() - start
    """)
    assert ("GT011", 5) in hits

    hits = rules_hit("""
        import time

        def g(lease_s):
            now = time.time()
            deadline = now + lease_s
            return deadline
    """)
    assert ("GT011", 6) in hits


def test_gt011_positive_duration_then_ms_conversion():
    # (time.time() - t0) * 1000 is interval math, NOT the exempt
    # epoch-ms constructor
    hits = rules_hit("""
        import time

        def f(t0):
            return (time.time() - t0) * 1000
    """)
    assert ("GT011", 5) in hits


def test_gt011_negative_epoch_ms_and_monotonic():
    # the epoch-ms DATA-timestamp constructor is exempt, either order
    assert rules_hit("""
        import time

        def stamp(ttl_ms):
            return int(time.time() * 1000) - ttl_ms

        def stamp2():
            now_ms = int(1000 * time.time())
            return now_ms + 3
    """) == []
    # monotonic interval math is the fix, not a finding
    assert rules_hit("""
        import time

        def f(start):
            return time.monotonic() - start
    """) == []
    # bare timestamps without arithmetic are fine
    assert rules_hit("""
        import time

        def g():
            return {"created": time.time()}
    """) == []
    # name tracking is scoped per function: a wall-clock `now` in one
    # function must not poison a monotonic `now` elsewhere
    assert rules_hit("""
        import time

        def stamp():
            now = time.time()
            return {"created": now}

        def elapsed(t0):
            now = time.monotonic()
            return now - t0
    """) == []


# ---------------------------------------------------------------------------
# GT012 unbounded blocking calls
# ---------------------------------------------------------------------------

def test_gt012_positive_flight_calls_without_options():
    hits = rules_hit("""
        def scan(client, ticket):
            reader = client.do_get(ticket)
            return reader.read_all()
    """)
    assert ("GT012", 3) in hits
    hits = rules_hit("""
        def put(conn, desc, schema):
            return conn.do_put(desc, schema)
    """)
    assert ("GT012", 3) in hits
    hits = rules_hit("""
        def act(conn, action):
            return list(conn.do_action(action))
    """)
    assert ("GT012", 3) in hits


def test_gt012_positive_urlopen_and_socket_without_timeout():
    hits = rules_hit("""
        import urllib.request

        def fetch(url):
            with urllib.request.urlopen(url) as r:
                return r.read()
    """)
    assert ("GT012", 5) in hits
    hits = rules_hit("""
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url).read()
    """)
    assert ("GT012", 5) in hits
    hits = rules_hit("""
        import socket

        def dial(addr):
            return socket.create_connection(addr)
    """)
    assert ("GT012", 5) in hits


def test_gt012_negative_bounded_calls():
    assert rules_hit("""
        import urllib.request

        def fetch(url):
            with urllib.request.urlopen(url, timeout=5.0) as r:
                return r.read()
    """, "GT012") == []
    # positional timeout forms count as explicit
    assert rules_hit("""
        import socket

        def dial(addr):
            return socket.create_connection(addr, 3.0)
    """, "GT012") == []
    # ... including on bare-name imports
    assert rules_hit("""
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url, None, 5.0).read()
    """, "GT012") == []
    assert rules_hit("""
        from socket import create_connection

        def dial(addr):
            return create_connection(addr, 3.0)
    """, "GT012") == []
    assert rules_hit("""
        import pyarrow.flight as flight

        def scan(client, ticket, timeout):
            return client.do_get(
                ticket, options=flight.FlightCallOptions(timeout=timeout)
            ).read_all()
    """, "GT012") == []
    # server-side dispatch plumbing is not a Flight client call
    assert rules_hit("""
        class Server:
            def do_action(self, context, action):
                return self._do_action(action.type)

            def handle(self, context, action):
                return self.do_action(context, action)
    """, "GT012") == []


def test_gt012_suppressible():
    act, sup = run_lint("""
        def stream(conn, desc, schema):
            # long-lived by design
            # gtlint: disable-next-line=GT012
            return conn.do_put(desc, schema)
    """, "GT012")
    assert act == [] and [f.rule for f in sup] == ["GT012"]


# ---------------------------------------------------------------------------
# GT017 metric naming conventions
# ---------------------------------------------------------------------------

def test_gt017_positive_counter_without_total():
    hits = rules_hit("""
        from greptimedb_tpu.telemetry.metrics import global_registry

        C = global_registry.counter("gtpu_things", "things counted")
    """, select="GT017")
    assert hits == [("GT017", 4)]


def test_gt017_positive_time_histogram_without_unit():
    hits = rules_hit("""
        H = global_registry.histogram(
            "gtpu_query_latency", "query latency",
        )
    """, select="GT017")
    assert [h[0] for h in hits] == ["GT017"]
    # _ms is as valid a unit suffix as _seconds
    assert rules_hit("""
        H = registry.histogram("gtpu_stage_duration_ms", "stage time")
    """, select="GT017") == []


def test_gt017_positive_uppercase_label():
    hits = rules_hit("""
        C = global_registry.counter(
            "gtpu_sheds_total", "sheds",
            labels=("Tenant", "reason"),
        )
    """, select="GT017")
    assert hits == [("GT017", 4)]


def test_gt017_negative_conforming_and_foreign_receivers():
    # conforming registrations: no findings
    assert rules_hit("""
        C = global_registry.counter(
            "gtpu_calls_total", "calls", labels=("db", "code"),
        )
        G = global_registry.gauge("gtpu_depth", "queue depth")
        H = self._registry.histogram(
            "gtpu_queue_time_seconds", "sojourn",
        )
        B = registry.histogram("gtpu_batch_rows", "rows per batch")
    """, select="GT017") == []
    # .counter()/.histogram() on a NON-registry receiver is not a
    # metric registration
    assert rules_hit("""
        n = collections.Counter()
        x = stats.counter("whatever")
        y = panel.histogram("Latency")
    """, select="GT017") == []


# ---------------------------------------------------------------------------
# GT018 untracked device dispatch
# ---------------------------------------------------------------------------

def test_gt018_positive_decorated_jit_called_host_scope():
    hits = rules_hit("""
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("g",))
        def prog(x, *, g):
            return x + g

        def serve(x):
            return prog(x, g=4)
    """, select="GT018")
    assert hits == [("GT018", 9)]


def test_gt018_positive_jit_assignment_called_host_scope():
    hits = rules_hit("""
        import jax

        touch = jax.jit(lambda x: x.sum())

        def warm(arrs):
            return float(touch(arrs))
    """, select="GT018")
    assert hits == [("GT018", 7)]


def test_gt018_negative_inside_device_call_scope():
    assert rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import device_trace

        @jax.jit
        def prog(x):
            return x * 2

        def serve(x):
            with device_trace.device_call("site", key=("k",)) as d:
                return d.run(prog, x)

        def serve_direct(x):
            with device_trace.device_call("site") as d:
                out = prog(x)
                d.executed()
                return out

        def serve_chained(x, stats):
            with stats.timed("ms"), device_trace.device_call("s") as d:
                return d.run(prog, x)

        def serve_lambda(x, session_exec):
            with device_trace.device_call("s") as d:
                return session_exec(lambda: d.run(prog, x))
    """, select="GT018") == []


def test_gt018_negative_device_scope_and_unknown_callees():
    # a call INSIDE jit scope is inlining (tracing), not a dispatch;
    # builder-returned programs (name assigned from a helper call) are
    # not provably jit-produced and stay silent
    assert rules_hit("""
        import jax

        @jax.jit
        def inner(x):
            return x + 1

        @jax.jit
        def outer(x):
            return inner(x) * 2

        def get_program():
            return jax.jit(lambda v: v)

        def serve(x):
            program = get_program()
            return program(x)
    """, select="GT018") == []


def test_gt018_nested_def_does_not_inherit_device_call_scope():
    hits = rules_hit("""
        import jax
        from greptimedb_tpu.telemetry import device_trace

        @jax.jit
        def prog(x):
            return x

        def serve(x):
            with device_trace.device_call("s") as d:
                def later():
                    return prog(x)
                return d.run(prog, x), later
    """, select="GT018")
    assert hits == [("GT018", 12)]


# ---------------------------------------------------------------------------
# GT019 unbounded I/O in scrape/heartbeat paths
# ---------------------------------------------------------------------------

def test_gt019_positive_collector_urlopen_unbounded():
    hits = rules_hit("""
        from urllib.request import urlopen
        from greptimedb_tpu.telemetry.metrics import global_registry

        def _collect():
            urlopen("http://peer:4000/metrics")

        global_registry.register_collector(_collect)
    """, select="GT019")
    assert hits == [("GT019", 6)]


def test_gt019_positive_heartbeat_builder_flight_call():
    hits = rules_hit("""
        def build_node_stats(inst):
            out = {}
            out["peer"] = inst.client.do_action("region_stats")
            return out
    """, select="GT019")
    assert hits == [("GT019", 4)]


def test_gt019_positive_pool_stats_hook_httpconn():
    hits = rules_hit("""
        import http.client
        from greptimedb_tpu.telemetry import memory

        def _pool_stats(pool):
            conn = http.client.HTTPConnection("peer", 80)
            return {}

        memory.register_pool("p", "host", object(), stats=_pool_stats)
    """, select="GT019")
    assert hits == [("GT019", 6)]


def test_gt019_positive_nested_def_inherits_hook_scope():
    hits = rules_hit("""
        from urllib.request import urlopen
        from greptimedb_tpu.telemetry.metrics import global_registry

        def _collect():
            def inner():
                urlopen("http://peer:4000/metrics")
            inner()

        global_registry.register_collector(_collect)
    """, select="GT019")
    assert hits == [("GT019", 7)]


def test_gt019_negative_bounded_and_off_path():
    # bounded calls in a hook are fine; the same unbounded calls
    # OUTSIDE a registered hook are not GT019's business (GT012 covers
    # the general case)
    assert rules_hit("""
        from urllib.request import urlopen
        from greptimedb_tpu.telemetry.metrics import global_registry

        def _collect():
            urlopen("http://peer:4000/metrics", timeout=2.0)
            cli.do_action("x", options=opts)

        global_registry.register_collector(_collect)

        def not_a_hook():
            urlopen("http://peer:4000/metrics")
    """, select="GT019") == []


# ---------------------------------------------------------------------------
# --changed mode
# ---------------------------------------------------------------------------

def test_changed_mode_lints_only_differing_files(tmp_path):
    """In a fresh git repo: clean committed file + dirty violating
    file; --changed HEAD flags only the dirty one."""
    import os

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=repo, check=True, capture_output=True,
            env={**os.environ, "GIT_AUTHOR_NAME": "t",
                 "GIT_AUTHOR_EMAIL": "t@t", "GIT_COMMITTER_NAME": "t",
                 "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-q")
    clean = repo / "clean.py"
    clean.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    dirty = repo / "dirty.py"
    dirty.write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # clean.py keeps its committed violation (must NOT be relinted);
    # dirty.py gains one (must be flagged); untracked.py is new
    dirty.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    untracked = repo / "untracked.py"
    untracked.write_text("def f(xs=[]):\n    return xs\n")

    from greptimedb_tpu.tools.lint import runner

    old_root = runner._REPO_ROOT
    runner._REPO_ROOT = str(repo)
    try:
        only = runner.changed_files("HEAD")
        assert only == {str(dirty), str(untracked)}
        res = runner.lint_paths([str(repo)], only=only)
    finally:
        runner._REPO_ROOT = old_root
    flagged = {d["path"].rsplit("/", 1)[-1] for d in res["findings"]}
    assert "dirty.py" in flagged and "untracked.py" in flagged
    assert "clean.py" not in flagged
    assert res["counts"]["files"] == 2


def test_changed_mode_cli_unknown_ref_exits_2(tmp_path):
    from greptimedb_tpu.tools.lint.runner import main as lint_main

    rc = lint_main(["--changed", "no-such-ref-xyz", str(tmp_path)])
    assert rc == 2


def test_changed_run_does_not_report_foreign_stale(tmp_path):
    """A --changed run must not mark baseline entries for UNSCANNED
    files as stale; a normal (full) run still must — that is how
    entries for DELETED files get flushed out."""
    import os

    target = tmp_path / "a.py"
    target.write_text("x = 1\n")
    base = Baseline([{
        "rule": "GT001", "path": "elsewhere/b.py", "line": 3,
        "text": "except Exception:",
    }])
    # --changed semantics: `only` restricts the walk, foreign entries
    # are out of scope
    res = lint_paths([str(target)], baseline=base,
                     only={os.path.normpath(str(target))})
    assert res["stale_baseline"] == []
    assert res["clean"]
    # full-run semantics: the unmatched entry is stale (deleted file)
    res = lint_paths([str(target)], baseline=base)
    assert len(res["stale_baseline"]) == 1
    assert not res["clean"]


# ---------------------------------------------------------------------------
# GT021 direct runtime-knob write
# ---------------------------------------------------------------------------

def test_gt021_positive_direct_and_augmented_write():
    hits = rules_hit("""
        def detune(inst, opts):
            inst.scheduler.config.max_concurrency = 4
            opts.l1_trigger_files += 2
            a, inst.compaction.opts.workers = 1, 8
    """, select="GT021")
    assert hits == [("GT021", 3), ("GT021", 4), ("GT021", 5)]


def test_gt021_positive_module_scope_write():
    hits = rules_hit("""
        import somewhere
        somewhere.cache.max_bytes = 1 << 20
    """, select="GT021")
    assert hits == [("GT021", 3)]


def test_gt021_negative_registry_self_and_config_appliers():
    hits = rules_hit("""
        class Cache:
            def __init__(self, n):
                self.max_bytes = n          # owning object

            def set_max_bytes(self, v):
                self.max_bytes = int(v)     # owning object

        def configure(inst, opts):
            inst.cache.max_bytes = opts.n   # process-start applier

        def from_options(o):
            o.scheduler.max_concurrency = 8

        def actuate(registry):
            registry.set("result_cache.bytes", 1 << 20)  # sanctioned
            max_bytes = 7                   # plain Name, not an attr
    """, select="GT021")
    assert hits == []


def test_gt021_negative_autotune_package_path():
    src = textwrap.dedent("""
        def apply(inst, v):
            inst.cache.max_bytes = int(v)
    """)
    act, _ = lint_source(
        "greptimedb_tpu/autotune/knobs.py", src, select={"GT021"})
    assert act == []
    # same source outside the package IS flagged
    act, _ = lint_source("greptimedb_tpu/other.py", src,
                         select={"GT021"})
    assert [f.rule for f in act] == ["GT021"]


# ---------------------------------------------------------------------------
# GT022 pallas_call hygiene
# ---------------------------------------------------------------------------

def test_gt022_positive_hardcoded_and_missing_interpret():
    hits = rules_hit("""
        import jax
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + x_ref[...]

        def run(x):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True,
            )(x)

        def run2(x):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
    """, select="GT022")
    assert hits == [("GT022", 9), ("GT022", 16)]


def test_gt022_negative_threaded_interpret():
    assert rules_hit("""
        import jax
        from jax.experimental import pallas as pl
        from greptimedb_tpu.parallel.kernels import interpret_mode

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + x_ref[...]

        def run(x, interpret):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=interpret,
            )(x)

        def run2(x):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=interpret_mode(),
            )(x)

        def run3(x, **kw):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                **kw,
            )(x)
    """, select="GT022") == []


def test_gt022_positive_unbound_device_id_axis():
    hits = rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def body(ref, o_ref):
                rdma = pltpu.make_async_remote_copy(
                    src_ref=ref, dst_ref=o_ref,
                    device_id=("time", 1),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
                rdma.start()

            return shard_map(body, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P("shard"))(x)
    """, select="GT022")
    assert hits == [("GT022", 9)]


def test_gt022_negative_bound_or_computed_device_id():
    # mesh-form device_id naming the bound axis: clean
    assert rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def body(ref, o_ref):
                rdma = pltpu.make_async_remote_copy(
                    src_ref=ref, dst_ref=o_ref,
                    device_id=("shard", 1),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
                rdma.start()

            return shard_map(body, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P("shard"))(x)
    """, select="GT022") == []
    # computed logical device id: identifiers are index arithmetic,
    # not axis names; the axis_index subtree is GT013's domain
    assert rules_hit("""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import PartitionSpec as P

        def run(mesh, x):
            def body(ref, o_ref):
                my = jax.lax.axis_index("shard")
                right = jax.lax.rem(my + 1, 4)
                rdma = pltpu.make_async_remote_copy(
                    src_ref=ref, dst_ref=o_ref,
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()

            return shard_map(body, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P("shard"))(x)
    """, select="GT022") == []
    # outside any shard_map body (a bare pallas kernel helper): no
    # binding to compare against, stays quiet
    assert rules_hit("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(ref, o_ref):
            rdma = pltpu.make_async_remote_copy(
                src_ref=ref, dst_ref=o_ref,
                device_id=("time", 1),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdma.start()
    """, select="GT022") == []


# ---------------------------------------------------------------------------
# GT033 full-label-plane predicate
# ---------------------------------------------------------------------------

def test_gt033_positive_compare_on_tag_values():
    hits = rules_hit("""
        import numpy as np

        def match(reg, value):
            vals = reg.tag_values("host")
            return np.flatnonzero(vals == value)
    """, select="GT033")
    assert ("GT033", 6) in hits


def test_gt033_positive_direct_call_and_codes_matrix():
    # compare directly on the call result, no intermediate name
    hits = rules_hit("""
        def match(reg, value):
            return reg.tag_values("host") != value
    """, select="GT033")
    assert ("GT033", 3) in hits
    # subscripted codes_matrix column through a local
    hits = rules_hit("""
        def match(reg, code, i):
            codes = reg.codes_matrix()
            return codes[:, i] == code
    """, select="GT033")
    assert ("GT033", 4) in hits


def test_gt033_positive_numpy_comparison_calls():
    hits = rules_hit("""
        import numpy as np

        def match(reg, wanted):
            vals = reg.tag_values("host")
            return np.isin(vals, wanted)
    """, select="GT033")
    assert ("GT033", 6) in hits


def test_gt033_negative_gathers_and_index_path():
    # gathering values by sid (no predicate) is the sanctioned use
    assert rules_hit("""
        def decode(reg, sids):
            return reg.tag_values("host")[sids]
    """, select="GT033") == []
    # routing through the index package is the fix, not a finding
    assert rules_hit("""
        from greptimedb_tpu import index

        def match(reg, value):
            return index.match_sids(reg, [("host", "eq", value)])
    """, select="GT033") == []
    # compares on unrelated arrays stay quiet
    assert rules_hit("""
        import numpy as np

        def f(rows, value):
            vals = rows.ts
            return np.flatnonzero(vals == value)
    """, select="GT033") == []


def test_gt033_negative_reassigned_name_untracked():
    # a name later rebound to something else is no longer the plane
    assert rules_hit("""
        def f(reg, other, value):
            vals = reg.tag_values("host")
            vals = other.column("host")
            return vals == value
    """, select="GT033") == []


def test_gt033_negative_exempt_paths():
    src = """\
def match(reg, value):
    vals = reg.tag_values("host")
    return vals == value
"""
    from greptimedb_tpu.tools.lint import lint_source
    for path in ("greptimedb_tpu/index/tag_index.py",
                 "greptimedb_tpu/storage/series.py"):
        act, _ = lint_source(path, src, select={"GT033"})
        assert act == [], path


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
