"""Tracing spans through the query path + /v1/traces (VERDICT rows
15/29: tracing subsystem)."""

import json
import urllib.request

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.telemetry import tracing


@pytest.fixture(autouse=True)
def _fresh_traces():
    tracing.global_traces.clear()
    yield
    tracing.global_traces.clear()


def test_span_nesting_and_attributes():
    with tracing.span("outer", who="me") as root:
        with tracing.span("inner") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        assert tracing.current_trace_id() == root.trace_id
    assert tracing.current_trace_id() is None
    spans = tracing.global_traces.trace(root.trace_id)
    names = {s["name"] for s in spans}
    assert names == {"outer", "inner"}
    outer = next(s for s in spans if s["name"] == "outer")
    assert outer["attributes"] == {"who": "me"}
    assert outer["duration_ms"] is not None


def test_span_error_recorded():
    with pytest.raises(ValueError):
        with tracing.span("boom") as sp:
            raise ValueError("nope")
    spans = tracing.global_traces.trace(sp.trace_id)
    assert "ValueError: nope" in spans[0]["attributes"]["error"]


def test_remote_traceparent_continues_trace():
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with tracing.start_remote(tp, "handler") as sp:
        assert sp.trace_id == "ab" * 16
        assert sp.parent_id == "cd" * 8
    # malformed -> fresh root
    with tracing.start_remote("garbage", "handler") as sp2:
        assert sp2.parent_id is None


def test_sql_pipeline_emits_spans(tmp_path):
    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    try:
        inst.sql("CREATE TABLE t (v DOUBLE, ts TIMESTAMP TIME INDEX)")
        inst.sql("INSERT INTO t (v, ts) VALUES (1.0, 1)")
        inst.sql("SELECT count(*) FROM t")
    finally:
        inst.close()
    all_traces = tracing.global_traces.traces()
    names = {
        s["name"] for tr in all_traces for s in tr["spans"]
    }
    assert "sql.Select" in names and "sql.Insert" in names
    assert "query.scan" in names
    # scan nests under the select statement
    for tr in all_traces:
        by_name = {s["name"]: s for s in tr["spans"]}
        if "query.scan" in by_name and "sql.Select" in by_name:
            assert (by_name["query.scan"]["parent_id"]
                    == by_name["sql.Select"]["span_id"])
            break
    else:
        raise AssertionError("no trace linked scan under select")


def test_http_traces_endpoint(tmp_path):
    from greptimedb_tpu.servers.http import HttpServer

    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    srv = HttpServer(inst, port=0).start()
    try:
        import urllib.parse

        data = urllib.parse.urlencode({"sql": "SELECT 1"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/sql", data=data,
            headers={"traceparent": "00-" + "11" * 16 + "-"
                     + "22" * 8 + "-01"},
        )
        urllib.request.urlopen(req, timeout=10)
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/traces/" + "11" * 16,
            timeout=10,
        ).read())
        names = {s["name"] for s in out["spans"]}
        assert "http /v1/sql" in names and "sql.Select" in names
    finally:
        srv.stop()
        inst.close()
