"""Tracing spans through the query path + /v1/traces (VERDICT rows
15/29: tracing subsystem)."""

import json
import urllib.request

import pytest

from greptimedb_tpu.instance import Standalone
from greptimedb_tpu.telemetry import tracing


@pytest.fixture(autouse=True)
def _fresh_traces():
    tracing.global_traces.clear()
    yield
    tracing.global_traces.clear()


def test_span_nesting_and_attributes():
    with tracing.span("outer", who="me") as root:
        with tracing.span("inner") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        assert tracing.current_trace_id() == root.trace_id
    assert tracing.current_trace_id() is None
    spans = tracing.global_traces.trace(root.trace_id)
    names = {s["name"] for s in spans}
    assert names == {"outer", "inner"}
    outer = next(s for s in spans if s["name"] == "outer")
    assert outer["attributes"] == {"who": "me"}
    assert outer["duration_ms"] is not None


def test_span_error_recorded():
    with pytest.raises(ValueError):
        with tracing.span("boom") as sp:
            raise ValueError("nope")
    spans = tracing.global_traces.trace(sp.trace_id)
    assert "ValueError: nope" in spans[0]["attributes"]["error"]


def test_remote_traceparent_continues_trace():
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with tracing.start_remote(tp, "handler") as sp:
        assert sp.trace_id == "ab" * 16
        assert sp.parent_id == "cd" * 8
    # malformed -> fresh root
    with tracing.start_remote("garbage", "handler") as sp2:
        assert sp2.parent_id is None


def test_sql_pipeline_emits_spans(tmp_path):
    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    try:
        inst.sql("CREATE TABLE t (v DOUBLE, ts TIMESTAMP TIME INDEX)")
        inst.sql("INSERT INTO t (v, ts) VALUES (1.0, 1)")
        inst.sql("SELECT count(*) FROM t")
    finally:
        inst.close()
    all_traces = tracing.global_traces.traces()
    names = {
        s["name"] for tr in all_traces for s in tr["spans"]
    }
    assert "sql.Select" in names and "sql.Insert" in names
    assert "query.scan" in names
    # scan nests under the select statement
    for tr in all_traces:
        by_name = {s["name"]: s for s in tr["spans"]}
        if "query.scan" in by_name and "sql.Select" in by_name:
            assert (by_name["query.scan"]["parent_id"]
                    == by_name["sql.Select"]["span_id"])
            break
    else:
        raise AssertionError("no trace linked scan under select")


def test_http_traces_endpoint(tmp_path):
    from greptimedb_tpu.servers.http import HttpServer

    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    srv = HttpServer(inst, port=0).start()
    try:
        import urllib.parse

        data = urllib.parse.urlencode({"sql": "SELECT 1"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/sql", data=data,
            headers={"traceparent": "00-" + "11" * 16 + "-"
                     + "22" * 8 + "-01"},
        )
        urllib.request.urlopen(req, timeout=10)
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/traces/" + "11" * 16,
            timeout=10,
        ).read())
        names = {s["name"] for s in out["spans"]}
        assert "http /v1/sql" in names and "sql.Select" in names
    finally:
        srv.stop()
        inst.close()


def test_configure_and_ring_bounds():
    cfg = tracing.configure({"sample_ratio": 0.5, "capacity": 7,
                             "slow_ms": 123.0})
    try:
        assert cfg.sample_ratio == 0.5
        assert tracing.global_traces.cap == 7
        assert not tracing.ring_unbounded()
        for i in range(20):
            with tracing.span(f"t{i}"):
                pass
        assert len(tracing.global_traces.traces(limit=100)) <= 7
        tracing.configure({"capacity": 0})
        assert tracing.ring_unbounded()
    finally:
        tracing.configure({})


def test_tail_sampling_drops_unremarkable_keeps_error_and_slow():
    tracing.configure({"sample_ratio": 0.0, "slow_ms": 50.0})
    try:
        # unremarkable root: dropped at decision time
        with tracing.span("boring") as sp:
            pass
        assert tracing.global_traces.trace(sp.trace_id) == []
        # errored trace: kept (error can be on a CHILD span)
        try:
            with tracing.span("root") as rsp:
                with tracing.span("child"):
                    raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert tracing.global_traces.trace(rsp.trace_id)
        # slow root: kept
        import time as _time

        with tracing.span("slowroot") as ssp:
            _time.sleep(0.06)
        assert tracing.global_traces.trace(ssp.trace_id)
        # mark_keep: kept
        with tracing.span("marked") as msp:
            tracing.mark_keep()
        assert tracing.global_traces.trace(msp.trace_id)
    finally:
        tracing.configure({})


def test_disabled_tracing_is_inert():
    tracing.configure({"enable": False})
    try:
        with tracing.span("x") as sp:
            assert sp.trace_id == ""
            assert tracing.current_trace_id() is None
            assert tracing.traceparent() is None
            with tracing.child_span("y") as c:
                c.attributes["k"] = 1  # writes land nowhere
        assert tracing.global_traces.traces() == []
    finally:
        tracing.configure({})


def test_child_span_without_trace_is_noop():
    with tracing.child_span("orphan") as sp:
        assert sp.trace_id == ""
    assert tracing.global_traces.traces() == []


def test_event_span_and_duration_monotonic():
    with tracing.span("root") as root:
        tracing.event_span("dist.merge", 12.5, stage="merge")
    spans = tracing.global_traces.trace(root.trace_id)
    ev = next(s for s in spans if s["name"] == "dist.merge")
    assert ev["duration_ms"] == 12.5
    assert ev["parent_id"] == root.span_id
    rt = next(s for s in spans if s["name"] == "root")
    # durations come off the monotonic clock: never negative
    assert rt["duration_ms"] is not None and rt["duration_ms"] >= 0


def test_export_and_ingest_spans_round_trip():
    with tracing.export_spans() as exported:
        with tracing.span("datanode.partial") as sp:
            with tracing.span("datanode.scan"):
                pass
    assert {s.name for s in exported} == {
        "datanode.partial", "datanode.scan"
    }
    docs = [s.to_json() for s in exported]
    tracing.global_traces.clear()
    tracing.ingest_spans(docs)
    spans = tracing.global_traces.trace(sp.trace_id)
    assert {s["name"] for s in spans} == {
        "datanode.partial", "datanode.scan"
    }


def test_render_tree_shape():
    with tracing.span("a") as a:
        with tracing.span("b"):
            pass
        with tracing.span("c", x=1):
            pass
    lines = tracing.render_tree(tracing.global_traces.trace(a.trace_id))
    assert lines[0].startswith("a ")
    assert all(ln.startswith("  ") for ln in lines[1:])
    assert any("{x=1}" in ln for ln in lines)


def test_traceparent_helper_and_remote_parenting():
    assert tracing.traceparent() is None
    with tracing.span("root") as sp:
        tp = tracing.traceparent()
        assert tp == f"00-{sp.trace_id}-{sp.span_id}-01"
    with tracing.start_remote(tp, "over-there") as rsp:
        assert rsp.trace_id == sp.trace_id
        assert rsp.parent_id == sp.span_id


def test_information_schema_traces_and_slow_query_trace_id(tmp_path):
    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    try:
        inst.slow_query_log.threshold_s = 0.0  # record everything
        inst.sql("CREATE TABLE t (v DOUBLE, ts TIMESTAMP TIME INDEX)")
        inst.sql("INSERT INTO t (v, ts) VALUES (1.0, 1)")
        inst.sql("SELECT count(*) FROM t")
        res = inst.sql("SELECT span_name, trace_id FROM "
                       "information_schema.traces")
        names = set(res.cols[0].values.tolist())
        assert "sql.Select" in names and "sql.execute" in names
        # slow-query entries carry the trace id of their statement
        entries = inst.slow_query_log.entries()
        assert entries and all(e["trace_id"] for e in entries)
        tids = {s for s in res.cols[1].values.tolist()}
        assert entries[-1]["trace_id"] in tids
        sq = inst.sql("SELECT trace_id FROM "
                      "information_schema.slow_queries")
        assert sq.num_rows == len(entries)
    finally:
        inst.close()


def test_explain_analyze_renders_span_tree(tmp_path):
    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    try:
        inst.sql("CREATE TABLE t (v DOUBLE, ts TIMESTAMP TIME INDEX)")
        inst.sql("INSERT INTO t (v, ts) VALUES (1.0, 1), (2.0, 2)")
        res = inst.sql("EXPLAIN ANALYZE SELECT count(*) FROM t")
        text = "\n".join(res.cols[0].values.tolist())
        assert "Trace:" in text
        assert "query.scan" in text
    finally:
        inst.close()


def test_device_spans_on_range_query(tmp_path):
    """prefer_device forces the grid path: the trace carries a
    device.execute span with compile/execute/readback attribution."""
    pytest.importorskip("jax")
    inst = Standalone(str(tmp_path / "data"), warm_start=False,
                      prefer_device=True)
    try:
        inst.sql("CREATE TABLE m (host STRING PRIMARY KEY, v DOUBLE, "
                 "ts TIMESTAMP TIME INDEX)")
        vals = ", ".join(
            f"('h{i % 3}', {i}.0, {1_700_000_000_000 + i * 1000})"
            for i in range(30)
        )
        inst.sql(f"INSERT INTO m (host, v, ts) VALUES {vals}")
        q = ("SELECT ts, host, avg(v) RANGE '10s' FROM m "
             "ALIGN '10s' BY (host)")
        with tracing.span("req") as root:
            inst.sql(q)
        spans = tracing.global_traces.trace(root.trace_id)
        dev = [s for s in spans if s["name"] == "device.execute"]
        assert dev, {s["name"] for s in spans}
        # the prelude dispatch carries its own span now; the range
        # program's span is the one with site=range
        sites = {s["attributes"]["site"] for s in dev}
        assert {"range", "range_prelude"} <= sites
        attrs = [s for s in dev
                 if s["attributes"]["site"] == "range"][0]["attributes"]
        assert attrs["compile"] == "first_call"
        assert attrs["readback_bytes"] > 0
        assert "execute_ms" in attrs
        # the program-profiler link rides the span
        assert attrs.get("program")
        # steady state: same program shape is a cache hit
        with tracing.span("req2") as root2:
            inst.sql(q)
        dev2 = [
            s for s in tracing.global_traces.trace(root2.trace_id)
            if s["name"] == "device.execute"
            and s["attributes"]["site"] == "range"
        ]
        assert dev2 and dev2[0]["attributes"]["compile"] == "cache_hit"
    finally:
        inst.close()


def test_http_traces_query_param_filter(tmp_path):
    from greptimedb_tpu.servers.http import HttpServer

    inst = Standalone(str(tmp_path / "data"), warm_start=False)
    srv = HttpServer(inst, port=0).start()
    try:
        import urllib.parse

        tid = "ab" * 16
        data = urllib.parse.urlencode({"sql": "SELECT 1"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/sql", data=data,
            headers={"traceparent": f"00-{tid}-{'cd' * 8}-01"},
        )
        urllib.request.urlopen(req, timeout=10)
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/traces?trace_id={tid}",
            timeout=10,
        ).read())
        assert out["trace_id"] == tid
        assert {s["name"] for s in out["spans"]} >= {"sql.Select"}
        # bounded listing with ?limit=
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/traces?limit=1",
            timeout=10,
        ).read())
        assert len(out["traces"]) <= 1
    finally:
        srv.stop()
        inst.close()


def test_child_exit_never_rolls_sampling_dice():
    """Only the process-local ROOT decides keep/drop: with
    sample_ratio=0, children (including ones under a remote parent)
    finishing early must not drop the in-flight trace before the root
    sees the error that makes it kept."""
    tracing.configure({"sample_ratio": 0.0})
    try:
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        try:
            with tracing.start_remote(tp, "datanode.partial"):
                with tracing.span("device.execute"):
                    pass  # unremarkable child exits first
                raise RuntimeError("late failure")
        except RuntimeError:
            pass
        spans = tracing.global_traces.trace("ab" * 16)
        assert {s["name"] for s in spans} == {
            "datanode.partial", "device.execute"
        }
    finally:
        tracing.configure({})


def test_malformed_traceparent_never_taints_trace_id():
    """Trace ids are client-controlled and spliced into hand-built
    ticket JSON: anything but strict lowercase hex starts a fresh
    root instead of inheriting the tainted id."""
    bad = [
        "00-" + 'x"' * 16 + "-" + "cd" * 8 + "-01",   # quote in id
        "00-" + "AB" * 16 + "-" + "cd" * 8 + "-01",   # uppercase hex
        "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",   # all-zero id
        "00-" + "ab" * 16 + "-" + "cd" * 8,           # missing flags
    ]
    for tp in bad:
        with tracing.start_remote(tp, "h") as sp:
            assert sp.parent_id is None, tp
            assert sp.trace_id not in tp


def test_sibling_root_drop_cannot_destroy_errored_trace():
    """Two concurrent local roots on one traceparent: the first root
    finishing unremarkably (sampled out) must not drop the trace while
    the second is still in flight and about to record an error."""
    tracing.configure({"sample_ratio": 0.0})
    try:
        tp = "00-" + "ef" * 16 + "-" + "ab" * 8 + "-01"
        b = tracing.start_remote(tp, "request-b")
        b.__enter__()
        # sibling A finishes first, unremarkable => would have dropped
        with tracing.start_remote(tp, "request-a"):
            pass
        assert tracing.global_traces.trace("ef" * 16), \
            "sibling drop destroyed the in-flight trace"
        try:
            raise RuntimeError("late error on B")
        except RuntimeError as e:
            b.__exit__(type(e), e, e.__traceback__)
        spans = tracing.global_traces.trace("ef" * 16)
        assert {s["name"] for s in spans} >= {"request-a", "request-b"}
    finally:
        tracing.configure({})
